"""One front door, two engines: the declarative row API driven through the
TPU engine (``engine="tpu"``) must return byte-identical rows — values,
nulls, stringified BINARY/FLBA/INT96, column order, projection, flat-guard
errors — vs the host engine, on every type the API serves.

This is the round-3 north-star integration: the parity API of the
reference (``ParquetReader.java:47-61,141-168``) served from fused
device-decoded columnar batches instead of per-cell virtual dispatch.
"""

import struct

import numpy as np
import pytest

from parquet_floor_tpu import (
    CompressionCodec,
    ParquetFileWriter,
    ParquetReader,
    WriterOptions,
    types,
)
from parquet_floor_tpu.api.hydrate import Hydrator


class _RowHydrator(Hydrator):
    def start(self):
        return []

    def add(self, target, heading, value):
        target.append((heading, value))
        return target

    def finish(self, target):
        return tuple(target)


def _rows(path, columns=None, engine="host"):
    return list(
        ParquetReader.stream_content(
            path, lambda cols: _RowHydrator(), columns, engine=engine
        )
    )


def _bits(v):
    """Bit-exact comparison key (floats compared by IEEE bit pattern)."""
    if isinstance(v, float):
        return struct.pack("<d", v)
    return v


def _assert_rows_equal(got, want):
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert len(g) == len(w)
        for (gh, gv), (wh, wv) in zip(g, w):
            assert gh == wh
            assert type(gv) is type(wv), (gh, gv, wv)
            assert _bits(gv) == _bits(wv), (gh, gv, wv)


def _write_wide(tmp_path, opts=None, n=700, groups=2):
    """A file touching every API-served physical type, with nulls, NaN,
    negative zero, empty and non-ASCII strings, raw binary, FLBA, INT96."""
    rng = np.random.default_rng(7)
    t = types
    schema = t.message(
        "t",
        t.required(t.INT64).named("i64"),
        t.optional(t.INT32).named("i32"),
        t.optional(t.DOUBLE).named("d"),
        t.required(t.FLOAT).named("f"),
        t.optional(t.BOOLEAN).named("b"),
        t.optional(t.BYTE_ARRAY).as_(t.string()).named("s"),
        t.required(t.BYTE_ARRAY).named("raw"),
        t.required(t.FIXED_LEN_BYTE_ARRAY).length(5).named("flba"),
        t.required(t.INT96).named("t96"),
    )
    specials = [float("nan"), float("inf"), -0.0, 2.0**-1074, 1e308]
    data = {
        "i64": [int(v) for v in rng.integers(-(2**62), 2**62, n)],
        "i32": [None if rng.random() < 0.2 else int(v)
                for v in rng.integers(-(2**31), 2**31, n)],
        "d": [None if rng.random() < 0.2
              else (specials[i % 5] if rng.random() < 0.1 else float(v))
              for i, v in enumerate(rng.standard_normal(n))],
        "f": [float(np.float32(v)) for v in rng.standard_normal(n)],
        "b": [None if rng.random() < 0.2 else bool(v)
              for v in rng.integers(0, 2, n)],
        "s": [None if rng.random() < 0.2
              else ["", "héllo", "x" * 40, f"s{i % 37}"][i % 4]
              for i in range(n)],
        "raw": [bytes([i % 256, (i * 7) % 256]) for i in range(n)],
        "flba": rng.integers(0, 256, (n, 5)).astype(np.uint8),
        "t96": rng.integers(0, 256, (n, 12)).astype(np.uint8),
    }
    path = str(tmp_path / "wide.parquet")
    opts = opts or WriterOptions(codec=CompressionCodec.SNAPPY)
    per = (n + groups - 1) // groups
    with ParquetFileWriter(path, schema, opts) as w:
        done = 0
        while done < n:
            take = min(per, n - done)
            w.write_columns({
                k: (v[done : done + take] if isinstance(v, list)
                    else v[done : done + take])
                for k, v in data.items()
            })
            done += take
    return path


@pytest.mark.parametrize("opts", [
    WriterOptions(codec=CompressionCodec.SNAPPY),
    WriterOptions(codec=CompressionCodec.ZSTD, page_version=1,
                  enable_dictionary=False),
    WriterOptions(codec=CompressionCodec.UNCOMPRESSED, delta_integers=True,
                  byte_stream_split_floats=True),
])
def test_row_parity_all_types(tmp_path, opts):
    path = _write_wide(tmp_path, opts)
    host = _rows(path)
    tpu = _rows(path, engine="tpu")
    _assert_rows_equal(tpu, host)


def test_row_parity_projection(tmp_path):
    path = _write_wide(tmp_path)
    for cols in (["i64"], ["s", "d"], ["flba", "t96", "b"], [], None,
                 ["does_not_exist"]):
        host = _rows(path, cols)
        tpu = _rows(path, cols, engine="tpu")
        _assert_rows_equal(tpu, host)
        if cols:
            want = [c for c in
                    ["i64", "i32", "d", "f", "b", "s", "raw", "flba", "t96"]
                    if c in cols]
            for row in tpu:
                assert [h for h, _ in row] == want


def test_flat_guard_parity(tmp_path):
    """Nested (repeated) files raise the same wrapped flat-guard error
    through both engines (reference ParquetReader.java:200-202)."""
    t = types
    schema = t.message(
        "t",
        t.required(t.INT64).named("id"),
        t.list_of(t.required(t.INT32).named("element"), "xs"),
    )
    path = str(tmp_path / "nested.parquet")
    with ParquetFileWriter(path, schema) as w:
        w.write_columns({"id": [1, 2, 3], "xs": [[1], [2, 3], []]})
    for engine in ("host", "tpu"):
        with pytest.raises(RuntimeError, match="Failed to read parquet") as ei:
            _rows(path, engine=engine)
        assert "Unexpected repetition" in repr(ei.value.__cause__ or ei.value)


def test_stream_closes_and_estimate(tmp_path):
    path = _write_wide(tmp_path, n=100, groups=1)
    r = ParquetReader.spliterator(path, lambda cols: _RowHydrator(),
                                  engine="tpu")
    assert r.estimate_size() == 100
    assert len(list(r)) == 100
    r.close()


def test_state_restore_tpu(tmp_path):
    path = _write_wide(tmp_path, n=300, groups=3)
    with ParquetReader.spliterator(path, lambda cols: _RowHydrator(),
                                   engine="tpu") as r:
        rows = []
        for _ in range(150):
            rows.append(next(r))
        st = r.state()
        rest = [*r]
    with ParquetReader.spliterator(path, lambda cols: _RowHydrator(),
                                   engine="tpu") as r2:
        r2.restore(st)
        resumed = [*r2]
    _assert_rows_equal(resumed, rest)
    host = _rows(path)
    _assert_rows_equal(rows + rest, host)


def test_auto_engine_on_cpu(tmp_path):
    """engine='auto' on the CPU test backend resolves to host and works."""
    path = _write_wide(tmp_path, n=50, groups=1)
    rows = _rows(path, engine="auto")
    _assert_rows_equal(rows, _rows(path))


def test_bad_engine_rejected(tmp_path):
    path = _write_wide(tmp_path, n=10, groups=1)
    with pytest.raises(ValueError, match="bad engine"):
        ParquetReader.spliterator(path, lambda cols: _RowHydrator(),
                                  engine="gpu")


def test_stream_content_to_strings_matches_tpu_rows(tmp_path):
    """The debug strings reader (host) agrees with stringified TPU rows."""
    path = _write_wide(tmp_path, n=60, groups=1)
    host_strs = list(ParquetReader.stream_content_to_strings(path))
    tpu = _rows(path, engine="tpu")
    for hs, row in zip(host_strs, tpu):
        got = [f"{h}={'null' if v is None else v}" for h, v in row]
        assert got == hs


def test_shared_pool_distinct_logical_types(tmp_path):
    """Two dict columns with byte-identical pools but different logical
    types (STRING vs raw BYTE_ARRAY) must render differently (utf-8 str
    vs hex) — the pool-cell cache must key on stringify semantics, not
    pool content alone."""
    t = types
    schema = t.message(
        "t",
        t.required(t.BYTE_ARRAY).as_(t.string()).named("s"),
        t.required(t.BYTE_ARRAY).named("raw"),
    )
    vals = [f"v{i % 5}" for i in range(500)]
    path = str(tmp_path / "twin.parquet")
    with ParquetFileWriter(path, schema) as w:
        w.write_columns({"s": vals, "raw": [v.encode() for v in vals]})
    host = _rows(path)
    tpu = _rows(path, engine="tpu")
    _assert_rows_equal(tpu, host)
    assert tpu[0][0][1] == "v0"                    # STRING → utf-8
    assert tpu[0][1][1] == "0x" + b"v0".hex().upper()  # raw → hex


def test_row_api_predicate_pushdown(tmp_path):
    """stream_content(predicate=...) skips statistics-pruned row groups
    before any page is read, identically on both engines; estimate_size
    reports the surviving rows."""
    from parquet_floor_tpu import col

    t = types
    schema = t.message("t", t.required(t.INT64).named("k"),
                       t.optional(t.BYTE_ARRAY).as_(t.string()).named("s"))
    path = str(tmp_path / "pred.parquet")
    with ParquetFileWriter(
        path, schema, WriterOptions(row_group_rows=100)
    ) as w:
        for g in range(5):
            w.write_columns({
                "k": list(range(g * 1000, g * 1000 + 100)),
                "s": [None if i % 9 == 0 else f"g{g}s{i}" for i in range(100)],
            })
    pred = col("k") >= 3000  # keeps groups 3, 4
    for engine in ("host", "tpu"):
        rows = list(ParquetReader.stream_content(
            path, lambda c: _RowHydrator(), engine=engine, predicate=pred
        ))
        assert len(rows) == 200, (engine, len(rows))
        assert rows[0][0] == ("k", 3000)
        assert rows[-1][0] == ("k", 4099)
    # both engines byte-identical under the predicate
    host = list(ParquetReader.stream_content(
        path, lambda c: _RowHydrator(), predicate=pred))
    tpu = list(ParquetReader.stream_content(
        path, lambda c: _RowHydrator(), engine="tpu", predicate=pred))
    _assert_rows_equal(tpu, host)
    with ParquetReader.spliterator(
        path, lambda c: _RowHydrator(), predicate=pred
    ) as r:
        assert r.estimate_size() == 200
    # a predicate nothing satisfies yields an empty stream, no error
    none = list(ParquetReader.stream_content(
        path, lambda c: _RowHydrator(), engine="tpu",
        predicate=col("k") < -5,
    ))
    assert none == []


def test_row_api_predicate_straddling_group_and_state(tmp_path):
    """Group-level semantics: a surviving group streams in full
    (including non-matching rows), and state()/restore() stay coherent
    under a predicate on both engines."""
    from parquet_floor_tpu import col

    t = types
    schema = t.message("t", t.required(t.INT64).named("k"))
    path = str(tmp_path / "strad.parquet")
    with ParquetFileWriter(
        path, schema, WriterOptions(row_group_rows=100)
    ) as w:
        for g in range(4):
            w.write_columns({"k": list(range(g * 100, g * 100 + 100))})
    pred = col("k") >= 150  # group 1 straddles: kept whole
    for engine in ("host", "tpu"):
        rows = [v for ((_, v),) in ParquetReader.stream_content(
            path, lambda c: _RowHydrator(), engine=engine, predicate=pred
        )]
        # groups 1..3 survive IN FULL (group-level pushdown, not rows)
        assert rows == list(range(100, 400)), (engine, rows[:3], len(rows))
    # checkpoint mid-first-surviving-group, restore into a fresh reader
    with ParquetReader.spliterator(
        path, lambda c: _RowHydrator(), engine="tpu", predicate=pred
    ) as r:
        first = [next(r) for _ in range(30)]
        st = r.state()
        rest = [*r]
    assert st["row_group"] == 1 and st["row_in_group"] == 30, st
    with ParquetReader.spliterator(
        path, lambda c: _RowHydrator(), engine="tpu", predicate=pred
    ) as r2:
        resumed = [*r2.restore(st)]
    assert resumed == rest
    assert [v for ((_, v),) in first + rest] == list(range(100, 400))


def test_bench_config_row_parity(tmp_path):
    """The five BASELINE configs' own workload generators, driven through
    both engines of the declarative row API: configs 1-4 must produce
    byte-identical rows; config 5 (nested) must refuse identically
    through both (the facade's flat guard)."""
    from benchmarks import workloads as w

    gens = [
        ("cfg1", lambda p: w.write_int64_plain(p, 3000)),
        ("cfg2", lambda p: w.write_lineitem(p, 2500, row_group_rows=800)),
        ("cfg3", lambda p: w.write_taxi_like(p, 2500)),
        ("cfg4", lambda p: w.write_wide_delta(p, n_rows=200, n_cols=40)),
    ]
    for name, gen in gens:
        path = str(tmp_path / f"{name}.parquet")
        gen(path)
        host = _rows(path)
        tpu = _rows(path, engine="tpu")
        _assert_rows_equal(tpu, host)
        assert len(host) > 0, name
    path5 = str(tmp_path / "cfg5.parquet")
    w.write_nested_list(path5, 500)
    for engine in ("host", "tpu"):
        with pytest.raises(RuntimeError, match="Failed to read parquet"):
            _rows(path5, engine=engine)


def test_dataset_row_stream_and_sharded(tmp_path):
    """Multi-file datasets: stream_content over a file list yields every
    file's rows in order (both engines, with schema enforcement), and
    read_dataset_sharded assembles the concatenated global arrays."""
    from jax.sharding import Mesh

    import jax
    from parquet_floor_tpu.parallel.multihost import read_dataset_sharded

    t = types
    schema = t.message("t", t.required(t.INT64).named("k"),
                       t.optional(t.BYTE_ARRAY).as_(t.string()).named("s"))
    paths = []
    for f in range(3):
        p = str(tmp_path / f"part{f}.parquet")
        with ParquetFileWriter(
            p, schema, WriterOptions(row_group_rows=40)
        ) as w:
            n = 100 + f * 10
            w.write_columns({
                "k": list(range(f * 1000, f * 1000 + n)),
                "s": [None if i % 7 == 0 else f"f{f}v{i}" for i in range(n)],
            })
        paths.append(p)
    expected_k = (
        list(range(0, 100)) + list(range(1000, 1110))
        + list(range(2000, 2120))
    )
    for engine in ("host", "tpu"):
        rows = list(ParquetReader.stream_content(
            paths, lambda c: _RowHydrator(), engine=engine
        ))
        assert [r[0][1] for r in rows] == expected_k, engine
    # schema mismatch at a file boundary fails loudly
    bad = str(tmp_path / "bad.parquet")
    s2 = t.message("t", t.required(t.INT32).named("k"))
    with ParquetFileWriter(bad, s2) as w:
        w.write_columns({"k": [1, 2]})
    with pytest.raises(ValueError, match="disagrees"):
        list(ParquetReader.stream_content(
            [paths[0], bad], lambda c: _RowHydrator()
        ))
    # logical-type drift is a schema mismatch too (str vs hex rendering)
    raw = str(tmp_path / "raw.parquet")
    s3 = t.message("t", t.required(t.INT64).named("k"),
                   t.optional(t.BYTE_ARRAY).named("s"))
    with ParquetFileWriter(raw, s3) as w:
        w.write_columns({"k": [1], "s": [b"x"]})
    with pytest.raises(ValueError, match="disagrees"):
        list(ParquetReader.stream_content(
            [paths[0], raw], lambda c: _RowHydrator()
        ))
    # a bare path into the dataset-sharded entry fails loudly
    from parquet_floor_tpu.parallel.multihost import read_dataset_sharded as rds
    with pytest.raises(TypeError, match="LIST of sources"):
        rds(paths[0], Mesh(np.array(jax.devices()).reshape(-1), ("rg",)))
    # the dataset stream exposes the single-file iterator surface
    it = ParquetReader.stream_content(paths, lambda c: _RowHydrator())
    assert it.metadata.row_groups and [c.path[0] for c in it.columns] == ["k", "s"]
    it.close()
    # sharded dataset read: global arrays preserve file-then-group order
    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("rg",))
    out = read_dataset_sharded(paths, mesh)
    kcol = out["k"]
    assert kcol.num_rows == len(expected_k)
    kv = np.asarray(kcol.values)
    rm = np.asarray(kcol.row_mask)
    np.testing.assert_array_equal(kv[rm], expected_k)
    sc = out["s"]
    lens = np.asarray(sc.lengths)
    rows_b = np.asarray(sc.values)
    mask = np.asarray(sc.mask)
    got_first = rows_b[np.flatnonzero(rm)[1]]
    ln = lens[np.flatnonzero(rm)[1]]
    assert got_first[:ln].tobytes().decode() == "f0v1"
    assert bool(mask[np.flatnonzero(rm)[0]])  # k=0 row: s is null (0 % 7)
    # predicate prunes groups across FILES: only file 2's rows survive
    from parquet_floor_tpu import col
    out_p = read_dataset_sharded(paths, mesh, predicate=col("k") >= 2000)
    kp = np.asarray(out_p["k"].values)
    rmp = np.asarray(out_p["k"].row_mask)
    assert out_p["k"].num_rows == 120
    np.testing.assert_array_equal(kp[rmp], list(range(2000, 2120)))
    # metadata/columns keep serving after exhaustion (the single-file
    # iterator serves its cached footer after close; datasets retain the
    # most recently opened file's)
    it2 = ParquetReader.stream_content(paths, lambda c: _RowHydrator())
    n_rows = sum(1 for _ in it2)
    assert n_rows == len(expected_k)
    assert it2.metadata.row_groups  # last file's footer, retained
    assert [c.path[0] for c in it2.columns] == ["k", "s"]
