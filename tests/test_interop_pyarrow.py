"""Interop golden tests: files written by pyarrow must read identically, and
files we write must read back identically under pyarrow (SURVEY.md §4:
"footer/Thrift golden tests against externally-generated files").
"""

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")
pq = pytest.importorskip("pyarrow.parquet")

from parquet_floor_tpu import (
    CompressionCodec,
    ParquetFileReader,
    ParquetFileWriter,
    WriterOptions,
    types,
)
from parquet_floor_tpu.format.encodings.plain import ByteArrayColumn

rng = np.random.default_rng(3)


def _table(n=2000):
    return pa.table(
        {
            "i64": pa.array(rng.integers(-(2**60), 2**60, n), type=pa.int64()),
            "i32": pa.array(rng.integers(-(2**30), 2**30, n), type=pa.int32()),
            "f64": pa.array(rng.standard_normal(n), type=pa.float64()),
            "f32": pa.array(rng.standard_normal(n).astype(np.float32), type=pa.float32()),
            "b": pa.array(rng.integers(0, 2, n).astype(bool)),
            "s": pa.array([f"value_{i % 37}" for i in range(n)]),
            "opt": pa.array(
                [None if i % 7 == 0 else int(i) for i in range(n)], type=pa.int64()
            ),
            "optstr": pa.array(
                [None if i % 11 == 0 else f"s{i % 5}" for i in range(n)], type=pa.string()
            ),
        }
    )


def _assert_matches_table(path, table):
    with ParquetFileReader(path) as r:
        assert r.record_count == table.num_rows
        cols = {}
        masks = {}
        nrows = 0
        for batch in r.iter_row_groups():
            for cb in batch.columns:
                name = cb.descriptor.path[0]
                dense, mask = cb.dense()
                cols.setdefault(name, []).append(dense)
                masks.setdefault(name, []).append(
                    mask if mask is not None else np.zeros(batch.num_rows, bool)
                )
            nrows += batch.num_rows
        assert nrows == table.num_rows
        for name in table.column_names:
            expected = table.column(name)
            mask = np.concatenate(masks[name])
            exp_null = np.array([v is None for v in expected.to_pylist()])
            np.testing.assert_array_equal(mask, exp_null, err_msg=f"null mask {name}")
            parts = cols[name]
            if isinstance(parts[0], ByteArrayColumn):
                got = []
                for p in parts:
                    got.extend(p.to_list())
                exp = [
                    (v.encode() if isinstance(v, str) else v) or b""
                    for v in expected.to_pylist()
                ]
                exp = [b"" if e is None else e for e in exp]
                assert got == exp, f"column {name} mismatch"
            else:
                got = np.concatenate(parts)
                exp_vals = expected.to_pandas().to_numpy()
                valid = ~exp_null
                np.testing.assert_array_equal(
                    got[valid],
                    exp_vals[valid].astype(got.dtype),
                    err_msg=f"column {name} mismatch",
                )


@pytest.mark.parametrize(
    "compression", ["NONE", "SNAPPY", "GZIP", "ZSTD", "BROTLI"]
)
@pytest.mark.parametrize("dictionary", [True, False])
def test_read_pyarrow_file(tmp_path, compression, dictionary):
    if compression != "NONE" and not pa.Codec.is_available(compression.lower()):
        pytest.skip(f"{compression} not built into pyarrow")
    if compression == "BROTLI":
        from parquet_floor_tpu.format import brotli_codec

        if not brotli_codec.available():
            pytest.skip("system brotli library not present")
    table = _table()
    path = tmp_path / "pa.parquet"
    pq.write_table(
        table, path, compression=compression, use_dictionary=dictionary,
        row_group_size=700,
    )
    _assert_matches_table(path, table)


@pytest.mark.parametrize("version", ["1.0", "2.4", "2.6"])
def test_read_pyarrow_format_versions(tmp_path, version):
    table = _table(500)
    path = tmp_path / "pa.parquet"
    pq.write_table(table, path, version=version)
    _assert_matches_table(path, table)


def test_read_pyarrow_v2_data_pages(tmp_path):
    table = _table(800)
    path = tmp_path / "pa.parquet"
    pq.write_table(table, path, data_page_version="2.0", compression="SNAPPY")
    _assert_matches_table(path, table)


def test_read_pyarrow_delta_encodings(tmp_path):
    n = 1000
    table = pa.table(
        {
            "d32": pa.array(np.cumsum(rng.integers(-5, 100, n)).astype(np.int32)),
            "d64": pa.array(np.cumsum(rng.integers(-5, 100, n)).astype(np.int64)),
            "dl": pa.array([f"str{i}" for i in range(n)]),
        }
    )
    path = tmp_path / "delta.parquet"
    pq.write_table(
        table, path, use_dictionary=False,
        column_encoding={"d32": "DELTA_BINARY_PACKED", "d64": "DELTA_BINARY_PACKED",
                         "dl": "DELTA_LENGTH_BYTE_ARRAY"},
    )
    _assert_matches_table(path, table)


def test_read_pyarrow_delta_byte_array(tmp_path):
    n = 500
    table = pa.table({"s": pa.array([f"prefix_common_{i:06d}" for i in range(n)])})
    path = tmp_path / "dba.parquet"
    pq.write_table(table, path, use_dictionary=False,
                   column_encoding={"s": "DELTA_BYTE_ARRAY"})
    _assert_matches_table(path, table)


def test_read_pyarrow_byte_stream_split(tmp_path):
    n = 500
    table = pa.table({"f": pa.array(rng.standard_normal(n), type=pa.float64())})
    path = tmp_path / "bss.parquet"
    pq.write_table(table, path, use_dictionary=False,
                   column_encoding={"f": "BYTE_STREAM_SPLIT"})
    _assert_matches_table(path, table)


def test_read_pyarrow_fixed_len_byte_array(tmp_path):
    n = 100
    vals = [bytes(rng.integers(0, 256, 8).astype(np.uint8)) for _ in range(n)]
    table = pa.table({"f": pa.array(vals, type=pa.binary(8))})
    path = tmp_path / "flba.parquet"
    pq.write_table(table, path)
    with ParquetFileReader(path) as r:
        col = r.read_row_group(0).columns[0]
        got = [bytes(row) for row in np.asarray(col.values)]
        assert got == vals


# ---------------------------------------------------------------------------
# our writer → pyarrow reader
# ---------------------------------------------------------------------------

def _our_file(tmp_path, options):
    n = 1500
    schema = types.message(
        "t",
        types.required(types.INT64).named("id"),
        types.optional(types.DOUBLE).named("score"),
        types.required(types.BYTE_ARRAY).as_(types.string()).named("name"),
        types.required(types.BOOLEAN).named("flag"),
        types.optional(types.INT32).named("cnt"),
        types.required(types.FLOAT).named("r"),
    )
    cols = {
        "id": np.arange(n, dtype=np.int64) * 3 - 1000,
        "score": [None if i % 6 == 0 else i * 0.5 for i in range(n)],
        "name": [f"name_{i % 23}" for i in range(n)],
        "flag": np.arange(n) % 3 == 0,
        "cnt": [None if i % 9 == 0 else i % 1000 for i in range(n)],
        "r": rng.standard_normal(n).astype(np.float32),
    }
    path = tmp_path / "ours.parquet"
    with ParquetFileWriter(path, schema, options) as w:
        w.write_columns(cols)
    return path, cols, n


def test_brotli_roundtrip_both_ways(tmp_path):
    """BROTLI out of the box: a pyarrow-written Brotli file reads exactly,
    and pyarrow reads a Brotli file our writer produced (VERDICT round-2
    missing #4 — the system-library codec behind the built-in seam)."""
    from parquet_floor_tpu.format import brotli_codec

    if not brotli_codec.available():
        pytest.skip("system brotli library not present")
    table = _table()
    path = tmp_path / "pab.parquet"
    pq.write_table(table, path, compression="BROTLI", row_group_size=700)
    _assert_matches_table(path, table)
    if brotli_codec.encoder_available():
        path2, cols, n = _our_file(
            tmp_path, WriterOptions(codec=CompressionCodec.BROTLI)
        )
        t2 = pq.read_table(path2)
        assert t2.num_rows == n
        assert t2.column("name").to_pylist() == cols["name"]
        np.testing.assert_array_equal(t2.column("id").to_numpy(), cols["id"])


@pytest.mark.parametrize(
    "codec",
    [CompressionCodec.UNCOMPRESSED, CompressionCodec.SNAPPY, CompressionCodec.GZIP,
     CompressionCodec.ZSTD],
)
@pytest.mark.parametrize("version", [1, 2])
def test_pyarrow_reads_our_file(tmp_path, codec, version):
    path, cols, n = _our_file(
        tmp_path, WriterOptions(codec=codec, page_version=version)
    )
    table = pq.read_table(path)
    assert table.num_rows == n
    np.testing.assert_array_equal(table.column("id").to_numpy(), cols["id"])
    assert table.column("score").to_pylist() == cols["score"]
    assert table.column("name").to_pylist() == cols["name"]
    np.testing.assert_array_equal(
        table.column("flag").to_numpy(), np.asarray(cols["flag"])
    )
    assert table.column("cnt").to_pylist() == cols["cnt"]
    np.testing.assert_array_equal(table.column("r").to_numpy(), cols["r"])


@pytest.mark.parametrize("version", [1, 2])
def test_pyarrow_reads_our_encodings(tmp_path, version):
    for opt in [
        WriterOptions(enable_dictionary=False, page_version=version),
        WriterOptions(enable_dictionary=False, delta_integers=True, page_version=version),
        WriterOptions(enable_dictionary=False, byte_stream_split_floats=True,
                      page_version=version),
        WriterOptions(data_page_values=128, page_version=version),
    ]:
        path, cols, n = _our_file(tmp_path, opt)
        table = pq.read_table(path)
        assert table.num_rows == n
        np.testing.assert_array_equal(table.column("id").to_numpy(), cols["id"])
        assert table.column("score").to_pylist() == cols["score"]


def test_pyarrow_sees_our_statistics(tmp_path):
    path, cols, n = _our_file(tmp_path, WriterOptions())
    meta = pq.read_metadata(path)
    col0 = meta.row_group(0).column(0)  # id
    assert col0.statistics.min == int(np.min(cols["id"]))
    assert col0.statistics.max == int(np.max(cols["id"]))
    assert col0.statistics.null_count == 0
    assert meta.num_rows == n


def test_pyarrow_roundtrip_metadata_created_by(tmp_path):
    path, *_ = _our_file(tmp_path, WriterOptions())
    meta = pq.read_metadata(path)
    assert "parquet-floor-tpu" in meta.created_by
