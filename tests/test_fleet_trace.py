"""Fleet-wide distributed tracing (docs/observability.md): request
contexts crossing real sockets with correct parent links and tenant
attribution, the clock-aligned timeline merge, the SLO/breaker/fence
flight recorder, histogram exemplars, and the cross-host metrics
scrape's dead-peer degradation."""

import json
import socket
import urllib.request

import pytest

from parquet_floor_tpu.serve import (
    DaemonClient,
    FleetCache,
    FleetMembership,
    ServeDaemon,
    Serving,
    SloTarget,
)
from parquet_floor_tpu.utils import trace
from parquet_floor_tpu.utils.histogram import LogHistogram, seed_exemplar_rng
from parquet_floor_tpu.utils.metrics_export import (
    MetricsServer,
    parse_prometheus,
    render_prometheus_snapshot,
)

KEY = ("fleet-trace", 1 << 20)


def content(offset: int, length: int) -> bytes:
    pat = f"ft:{offset}:{length}:".encode("ascii")
    return (pat * (length // len(pat) + 1))[:length]


def origin_read(key, ranges):
    return [content(o, n) for (o, n) in ranges]


@pytest.fixture()
def fleet2(tmp_path):
    """Two daemons over one origin, flight recording into tmp_path."""
    node_ids = ["a", "b"]
    membership = FleetMembership.create(node_ids)
    servings, fleets, daemons = [], [], []
    mdir = str(tmp_path / "metrics")
    fdir = str(tmp_path / "flight")
    import os

    os.makedirs(mdir)
    os.makedirs(fdir)
    try:
        for nid in node_ids:
            srv = Serving(prefetch_bytes=4 << 20)
            fc = FleetCache(nid, membership, origin=origin_read,
                            peer_timeout_s=1.0, breaker_threshold=2,
                            breaker_cooldown_s=0.15)
            d = ServeDaemon(srv, {}, fleet=fc, max_inflight=4,
                            max_pending=32, drain_timeout_s=3.0,
                            metrics_dir=mdir, flight_dir=fdir,
                            flight_debounce_s=0.0)
            d.start()
            servings.append(srv)
            fleets.append(fc)
            daemons.append(d)
        peers = {nid: ("127.0.0.1", d.port)
                 for nid, d in zip(node_ids, daemons)}
        for fc in fleets:
            fc.install_membership(membership, peers)
        yield fleets, daemons, fdir
    finally:
        for d in daemons:
            d.close()
        for fc in fleets:
            fc.close()
        for srv in servings:
            srv.close()


# --- context propagation over real sockets ----------------------------------

def test_daemon_client_socket_propagation(tmp_path):
    """DaemonClient -> ServeDaemon: the daemon-side span joins the
    client's trace, parented on the client-side request span, with the
    connection's tenant stamped on."""
    tracer = trace.Tracer(enabled=True)
    with Serving(prefetch_bytes=4 << 20) as srv, \
            ServeDaemon(srv, {}) as daemon:
        with DaemonClient("127.0.0.1", daemon.port, "acme") as c, \
                trace.using(tracer), \
                trace.use_flight_recorder(daemon._flight), \
                trace.start_trace("req") as h:
            tid = trace.current_context().trace_id
            c.request("lookup", dataset="none", key=1)
        frags = [t for t in daemon._flight.traces()
                 if t["trace_id"] == tid]
        assert frags, "request trace never sealed into the flight ring"
        spans = {s["name"]: s for s in frags[0]["spans"]}
        cli = spans["serve.client_request"]
        srvspan = spans["serve.daemon_request"]
        root = spans["req"]
        assert cli["parent_id"] == root["span_id"]
        assert srvspan["parent_id"] == cli["span_id"]
        assert srvspan["tenant"] == "acme"
        assert tracer.counters().get("trace.ctx_propagated", 0) == 0
        assert daemon.tracer.counters().get("trace.ctx_propagated", 0) \
            + sum(t.counters().get("trace.ctx_propagated", 0)
                  for t in [daemon.serving.tenant("acme").tracer]) >= 1


def test_fleet_peer_hop_joins_the_trace(fleet2):
    """A peer fetch lands a serve.fleet_serve span in the OWNER's
    flight ring, carrying the asker's trace_id and parented on the
    asker's serve.fleet_peer_fetch span."""
    fleets, daemons, _ = fleet2
    tracer = trace.Tracer(enabled=True)
    ranges = [(i * 4096, 512) for i in range(16)]
    tids = []
    for fc, d in zip(fleets, daemons):
        with trace.using(tracer), \
                trace.use_flight_recorder(d._flight), \
                trace.start_trace("fleet_req"):
            tids.append(trace.current_context().trace_id)
            got = fc.read_through(KEY, ranges,
                                  lambda rs: origin_read(KEY, rs))
        assert [bytes(b) for b in got] == [content(o, n)
                                           for (o, n) in ranges]
    # find a hop: owner-side serve.fleet_serve span in one ring whose
    # parent is an asker-side serve.fleet_peer_fetch span in the other
    frags = {}
    for d in daemons:
        for t in d._flight.traces():
            frags.setdefault(t["trace_id"], []).extend(
                (d._flight.host, s) for s in t["spans"])
    hops = 0
    for tid in tids:
        spans = frags.get(tid, [])
        by_id = {s["span_id"]: (host, s) for host, s in spans}
        for host, s in spans:
            if s["name"] != "serve.fleet_serve":
                continue
            parent = by_id.get(s["parent_id"])
            assert parent is not None, "hop's parent never recorded"
            phost, pspan = parent
            # a first-level hop parents on the asker's peer_fetch; a
            # replication push parents on the OWNER's own fleet_serve
            assert pspan["name"] in ("serve.fleet_peer_fetch",
                                     "serve.fleet_serve")
            assert phost != host, "hop did not cross hosts"
            if pspan["name"] == "serve.fleet_peer_fetch":
                hops += 1
    assert hops >= 1, "no traced request took a peer hop"


def test_peer_clock_offsets_sampled(fleet2):
    fleets, daemons, _ = fleet2
    tracer = trace.Tracer(enabled=True)
    with trace.using(tracer):
        fleets[0].read_through(KEY, [(0, 512), (1 << 20, 512)],
                               lambda rs: origin_read(KEY, rs))
    offs = fleets[0].clock_offsets()
    # same host, so the estimate is near zero but PRESENT for any peer
    # that answered
    for member, off in offs.items():
        assert abs(off) < 1.0, (member, off)


# --- the clock-aligned merge -------------------------------------------------

def test_merge_rebases_injected_skew():
    """Two nodes, node b's clock 5 s ahead; a's midpoint measurement
    says so; the merge must pull b's spans back onto a's axis."""
    t0 = trace.perf_to_unix(0.0) + 1000.0
    snap_a = {
        "node": "a",
        "clock_offsets": {"b": 5.0},
        "traces": [{
            "trace_id": "t1", "sealed_ts": t0 + 1,
            "spans": [{"trace_id": "t1", "span_id": "s1",
                       "parent_id": None, "name": "root",
                       "ts": t0, "dur": 0.2, "tid": 1}],
        }],
    }
    snap_b = {
        "node": "b",
        "traces": [{
            "trace_id": "t1", "sealed_ts": t0 + 6,
            "spans": [{"trace_id": "t1", "span_id": "s2",
                       "parent_id": "s1", "name": "hop",
                       "ts": t0 + 5.05, "dur": 0.1, "tid": 7}],
        }],
    }
    merged = trace.merge_fleet_trace([snap_a, snap_b])
    assert merged["clock_offsets_s"] == {"a": 0.0, "b": 5.0}
    xs = {e["args"]["span_id"]: e for e in merged["traceEvents"]
          if e.get("ph") == "X"}
    # b's span lands 50 ms after a's root, not 5.05 s
    assert xs["s2"]["ts"] - xs["s1"]["ts"] == pytest.approx(50_000, abs=1)
    v = trace.verify_fleet_timeline(merged)
    assert v["ok"] and v["cross_node_traces"] == ["t1"]


def test_compose_offsets_chains_through_reference():
    # a measured b at +2, b measured c at +3: c is +5 vs a
    out = trace._compose_offsets(
        ["a", "b", "c"], {"a": {"b": 2.0}, "b": {"c": 3.0}})
    assert out == {"a": 0.0, "b": 2.0, "c": 5.0}
    # unreachable nodes fall back to 0 rather than vanishing
    out = trace._compose_offsets(["a", "z"], {})
    assert out == {"a": 0.0, "z": 0.0}


# --- the flight recorder -----------------------------------------------------

def test_slo_burn_dumps_incident_bundle(tmp_path):
    """A breaching tenant's check_slos tick fires the flight trigger
    and the daemon dumps a bundle named for the reason."""
    fdir = str(tmp_path / "flight")
    import os

    os.makedirs(fdir)
    with Serving(prefetch_bytes=4 << 20) as srv, \
            ServeDaemon(srv, {}, flight_dir=fdir,
                        flight_debounce_s=0.0) as daemon:
        tn = srv.tenant("burny")
        srv.set_slo("burny", SloTarget(p99_seconds=0.002))
        for _ in range(100):
            tn.tracer.observe("serve.lookup_seconds", 0.05)
        statuses = srv.check_slos(now=30.0)
        assert statuses["burny"].breach
        bundles = sorted(p for p in os.listdir(fdir)
                         if p.startswith("incident-"))
        assert bundles, "SLO burn produced no incident bundle"
        with open(os.path.join(fdir, bundles[-1], "meta.json")) as f:
            meta = json.load(f)
        assert meta["reason"] == "slo_breach"
        assert meta["detail"]["tenant"] == "burny"
        for name in ("traces.json", "timeline.json", "health.txt"):
            assert os.path.exists(os.path.join(fdir, bundles[-1], name))


def test_flight_dump_debounce(tmp_path, monkeypatch):
    fdir = str(tmp_path / "f")
    import os

    os.makedirs(fdir)
    with Serving(prefetch_bytes=4 << 20) as srv, \
            ServeDaemon(srv, {}, flight_dir=fdir,
                        flight_debounce_s=3600.0) as daemon:
        assert trace.flight_fire("breaker_trip", {}) >= 1
        assert trace.flight_fire("breaker_trip", {}) >= 1
        bundles = [p for p in os.listdir(fdir)
                   if p.startswith("incident-")]
        assert len(bundles) == 1, "debounce did not hold"


def test_flight_recorder_ring_bounds():
    rec = trace.FlightRecorder(host="h", max_traces=2,
                               max_spans_per_trace=2)
    for i in range(4):
        tid = f"t{i}"
        # three nested spans enter, then exit innermost-first; the
        # trace seals when the outermost closes — one span over cap
        for _ in range(3):
            rec.begin(tid)
        for j in range(3):
            rec.end({"trace_id": tid, "span_id": f"s{i}.{j}",
                     "parent_id": None, "name": "x", "ts": float(i),
                     "dur": 0.0, "tid": 1})
    out = rec.traces()
    assert len(out) == 2  # ring kept the 2 newest
    assert [t["trace_id"] for t in out] == ["t2", "t3"]
    assert all(len(t["spans"]) == 2 for t in out)  # span cap held
    st = rec.stats()
    assert st["dropped_traces"] == 2
    assert st["dropped_spans"] == 4  # one per trace


# --- exemplars ---------------------------------------------------------------

def test_exemplar_reservoir_deterministic_under_seed():
    def build():
        seed_exemplar_rng(42)
        h = LogHistogram()
        for i in range(200):
            h.record(0.001 * (i + 1), exemplar=f"trace{i}")
        return h.exemplars

    a, b = build(), build()
    assert a == b and a  # same slots, and some were filled


def test_exemplar_round_trip_and_render():
    h = LogHistogram()
    assert h.record(0.5, exemplar="deadbeef") is True
    d = h.as_dict()
    assert "exemplars" in d
    h2 = LogHistogram.from_dict(d)
    assert h2.exemplars == h.exemplars
    # absent exemplars key stays absent (back-compat)
    assert "exemplars" not in LogHistogram().as_dict()
    snap = {"counters": {}, "gauges": {}, "histograms": {"x": d}}
    text = render_prometheus_snapshot(snap)
    assert '# {trace_id="deadbeef"}' in text
    samples = parse_prometheus(text)
    # the exemplar suffix did not break the scrape parse
    assert samples['pftpu_x_bucket{le="0.5"}'] == 1.0
    assert samples["pftpu_x_count"] == 1.0


def test_no_exemplar_without_active_trace():
    t = trace.Tracer(enabled=True)
    with trace.using(t):
        trace.observe("io.remote.get_seconds.primary", 0.01)
        assert all(not h.exemplars
                   for h in t.histograms().values())
        with trace.start_trace("r"):
            trace.observe("io.remote.get_seconds.primary", 0.01)
        assert any(h.exemplars for h in t.histograms().values())
        assert t.counters().get("trace.exemplars_recorded", 0) >= 1


# --- cross-host metrics scrape ----------------------------------------------

def test_metrics_server_folds_live_peer_and_counts_dead_one():
    # a port that refuses: bind-then-close
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    tracer = trace.Tracer(enabled=True)
    with Serving(prefetch_bytes=4 << 20) as srv, \
            ServeDaemon(srv, {}) as daemon:
        with trace.using(daemon.tracer):
            trace.count("serve.daemon_requests", 7)
        with MetricsServer(tracer, port=0,
                           peers=[("127.0.0.1", daemon.port),
                                  ("127.0.0.1", dead_port)],
                           peer_timeout_s=0.5) as ms:
            js = json.loads(urllib.request.urlopen(
                ms.url("/metrics.json"), timeout=5).read().decode())
    # the live peer's counters folded in; the dead one became a count,
    # visible in THIS scrape — never a failed scrape
    assert js["counters"].get("serve.daemon_requests", 0) >= 7
    assert js["counters"]["serve.metrics_peer_unreachable"] == 1


def test_new_names_are_registered():
    from parquet_floor_tpu.utils.trace import names

    for n in ("trace.ctx_propagated", "trace.exemplars_recorded",
              "trace.flight_spans_dropped", "trace.flight_traces_dropped",
              "serve.flight_dumps", "serve.metrics_peer_unreachable"):
        assert n in names.COUNTERS
    assert "trace.clock_offset_us" in names.GAUGES
    for n in ("serve.client_request", "serve.daemon_request",
              "serve.fleet_peer_fetch", "serve.fleet_serve",
              "serve.fleet_origin_read"):
        assert n in names.SPANS
    assert "serve.flight" in names.DECISIONS
