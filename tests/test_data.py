"""Training input pipeline (``parquet_floor_tpu.data``): deterministic
seeded order plans, carry-over batching, host sharding, and the
checkpoint/resume contract (``docs/data.md``).

The load-bearing claims pinned here: same seed ⇒ bit-identical batch
stream on every run (host and device faces); a loader restored from
``state()`` at ANY batch index emits exactly the remaining stream of the
uninterrupted run; host shards are disjoint and depend only on the
shard's units (not the fleet size); fault-injected transient retries
never perturb the stream; and the scanner/engine order plumbing the
loader rides (``DatasetScanner(order=...)``, windowed
``iter_dataset_row_groups``) delivers permuted units bit-identically to
the eager per-file loop."""

import json

import numpy as np
import pytest

from parquet_floor_tpu import (
    ParquetFileReader,
    ReaderOptions,
    UnsupportedFeatureError,
    trace,
)
from parquet_floor_tpu.data import (
    DataLoader,
    EpochPlan,
    Unit,
    keyed_rng,
    shard_units,
)
from parquet_floor_tpu.data.batcher import ColumnSpec, RowBuffer, make_batch
from parquet_floor_tpu.scan import DatasetScanner, ScanOptions
from parquet_floor_tpu.testing import FaultInjectingSource

from tests.test_scan import _write

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    d = tmp_path_factory.mktemp("data_ds")
    return [_write(str(d / f"f{i}.parquet"), seed=i) for i in range(4)]


def _batch_bytes(b):
    """One batch's full content as comparable bytes (valid rows only:
    the pad-width HWM may differ across faces, never the values)."""
    out = []
    n = b.num_valid
    for c in b.columns:
        v = np.asarray(c.values)
        if v.ndim == 2 and c.lengths is not None:
            ln = np.asarray(c.lengths)[:n].astype(np.int64)
            out.append(ln.tobytes())
            out.append(b"".join(
                v[i, : ln[i]].tobytes() for i in range(n)
            ))
        elif c.mask is not None:
            # zero the null slots: their payload is unspecified (the
            # faces fill them differently), only the mask is contractual
            m = np.asarray(c.mask)[:n]
            out.append(np.where(m, np.zeros_like(v[:n]), v[:n]).tobytes())
        else:
            out.append(v[:n].tobytes())
        if c.mask is not None:
            out.append(np.asarray(c.mask)[:n].tobytes())
    return b"".join(out)


def _stream(paths, engine="host", restore_at=None, loader_kw=None,
            batch=256, **kw):
    """The loader's full batch stream as bytes; ``restore_at=k`` runs a
    first loader to batch ``k``, checkpoints through JSON (the state
    must survive serialization), and collects the rest from a fresh
    restored loader."""
    kw.setdefault("shuffle_seed", 7)
    kw.setdefault("shuffle_window", 512)
    kw.setdefault("num_epochs", 2)
    kw.setdefault("drop_remainder", False)
    kw.update(loader_kw or {})
    ld = DataLoader(paths, batch, engine=engine, **kw)
    out = []
    if restore_at is not None:
        it = iter(ld)
        for _ in range(restore_at):
            next(it)
        state = json.loads(json.dumps(ld.state()))
        ld.close()
        ld = DataLoader(paths, batch, engine=engine, **kw).restore(state)
    for b in ld:
        out.append(_batch_bytes(b))
    ld.close()
    return out


# ---------------------------------------------------------------------------
# order plan math
# ---------------------------------------------------------------------------


def test_keyed_rng_is_counter_based():
    a = keyed_rng(7, 2, 3, 5).permutation(100)
    b = keyed_rng(7, 2, 3, 5).permutation(100)
    assert np.array_equal(a, b)
    assert not np.array_equal(a, keyed_rng(7, 2, 4, 5).permutation(100))
    assert not np.array_equal(a, keyed_rng(8, 2, 3, 5).permutation(100))


def test_shard_units_disjoint_cover_contiguous():
    units = [Unit(i // 2, i % 2, 100 + i) for i in range(10)]
    for hc in (1, 2, 3, 4, 10, 11):
        shards = [shard_units(units, h, hc) for h in range(hc)]
        flat = [u for s in shards for u in s]
        assert flat == units  # contiguous blocks, in order, covering all
    with pytest.raises(ValueError):
        shard_units(units, 2, 2)
    with pytest.raises(ValueError):
        shard_units(units, 0, 0)


def test_epoch_plan_permutation_keyed_on_seed_and_epoch():
    units = [Unit(0, i, 10) for i in range(16)]
    p0 = EpochPlan(units, 7, 0).units
    assert EpochPlan(units, 7, 0).units == p0
    assert EpochPlan(units, 7, 1).units != p0
    assert EpochPlan(units, 8, 0).units != p0
    assert sorted(p0) == sorted(units)
    assert EpochPlan(units, None, 0).units == units  # no seed: file order


def test_epoch_plan_window_blocks_never_span_units():
    units = [Unit(0, 0, 700), Unit(0, 1, 300)]
    plan = EpochPlan(units, 3, 0, window=256)
    for pos, u in enumerate(plan.units):
        perm = plan.unit_perm(pos)
        assert perm.shape == (u.num_rows,)
        assert np.array_equal(np.sort(perm), np.arange(u.num_rows))
        # each 256-row block permutes within itself (the tail is short)
        for off in range(0, u.num_rows, 256):
            blk = perm[off : off + 256]
            lo, hi = off, min(off + 256, u.num_rows)
            assert blk.min() >= lo and blk.max() < hi
    assert plan.unit_perm(0) is not None
    assert EpochPlan(units, 3, 0, window=0).unit_perm(0) is None
    assert EpochPlan(units, 3, 0, window=1).unit_perm(0) is None


def test_epoch_plan_resume_arithmetic():
    units = [Unit(0, 0, 700), Unit(0, 1, 300), Unit(1, 0, 500)]
    plan = EpochPlan(units, None, 0)
    assert plan.total_rows == 1500
    assert plan.n_batches(256, True) == 5
    assert plan.n_batches(256, False) == 6
    assert plan.resume_point(0, 256) == (0, 0)
    assert plan.resume_point(2, 256) == (0, 512)
    assert plan.resume_point(3, 256) == (1, 68)   # 768 - 700
    assert plan.resume_point(5, 256) == (2, 280)  # 1280 - 1000
    with pytest.raises(ValueError):
        plan.locate_row(1500)
    with pytest.raises(ValueError):
        EpochPlan(units, None, 0, window=256)  # window needs a seed


# ---------------------------------------------------------------------------
# batcher
# ---------------------------------------------------------------------------


def _spec(name="x"):
    class D:  # minimal stand-in descriptor
        path = (name,)
    return ColumnSpec(name=name, descriptor=D(), is_string=False,
                      has_mask=False)


def test_row_buffer_carry_over_and_alignment():
    spec = _spec()
    buf = RowBuffer([spec], np, {})
    buf.push([(np.arange(10), None, None)], 10)
    buf.push([(np.arange(10, 17), None, None)], 7)
    (v, m, ln), = buf.take(12)
    assert np.array_equal(v, np.arange(12)) and m is None and ln is None
    assert buf.rows == 5
    (v2, _, _), = buf.take(5)
    assert np.array_equal(v2, np.arange(12, 17))
    with pytest.raises(ValueError):
        buf.take(1)


def test_row_buffer_push_skip_drops_head():
    spec = _spec()
    buf = RowBuffer([spec], np, {})
    buf.push([(np.arange(10), None, None)], 10, skip=4)
    assert buf.rows == 6
    (v, _, _), = buf.take(6)
    assert np.array_equal(v, np.arange(4, 10))


def test_make_batch_pads_and_masks_tail():
    spec = _spec()
    b = make_batch([spec], [(np.arange(3.0), None, None)], epoch=1,
                   index=9, batch_size=8, valid=3, xp=np)
    assert b.epoch == 1 and b.index == 9
    assert b.batch_size == 8 and b.num_valid == 3
    assert np.array_equal(np.asarray(b.row_mask),
                          np.arange(8) < 3)
    v = np.asarray(b.columns[0].values)
    assert np.array_equal(v[:3], np.arange(3.0)) and not v[3:].any()
    full = make_batch([spec], [(np.arange(8.0), None, None)], 0, 0, 8, 8, np)
    assert full.row_mask is None


# ---------------------------------------------------------------------------
# loader: determinism, shuffling, sharding (host face)
# ---------------------------------------------------------------------------


def test_same_seed_same_stream_across_runs(dataset):
    s1 = _stream(dataset)
    s2 = _stream(dataset)
    assert s1 == s2 and len(s1) > 20


def test_shuffle_reorders_but_preserves_the_multiset(dataset):
    ref = _stream(dataset, shuffle_seed=None, shuffle_window=0,
                  num_epochs=1)
    shuf = _stream(dataset, num_epochs=1)
    assert shuf != ref
    with ParquetFileReader(dataset[0]) as r:
        pass

    def keys(stream_kw):
        out = []
        with DataLoader(dataset, 256, num_epochs=1, drop_remainder=False,
                        **stream_kw) as ld:
            for b in ld:
                out.append(np.asarray(b.column("k").values)[: b.num_valid])
        return np.sort(np.concatenate(out))

    assert np.array_equal(
        keys(dict(shuffle_seed=7, shuffle_window=512)),
        keys(dict(shuffle_seed=None)),
    )


def test_epochs_differ_but_replay(dataset):
    s = _stream(dataset, num_epochs=2)
    per_epoch = len(s) // 2
    assert s[:per_epoch] != s[per_epoch:]  # epoch 1 reshuffles
    assert _stream(dataset, num_epochs=2) == s


def test_shards_are_disjoint_and_cover(dataset):
    def keys(shard):
        out = [np.zeros(0, np.int64)]
        with DataLoader(dataset, 64, shuffle_seed=5, num_epochs=1,
                        drop_remainder=False, shard=shard) as ld:
            for b in ld:
                out.append(np.asarray(b.column("k").values)[: b.num_valid])
        return np.concatenate(out)

    whole = np.sort(keys((0, 1)))
    parts = [keys((h, 3)) for h in range(3)]
    assert sum(len(p) for p in parts) == len(whole)
    assert np.array_equal(np.sort(np.concatenate(parts)), whole)


def test_stream_depends_only_on_the_shard_units(dataset):
    # 4 files x 2 groups = 8 units: ceil(8/4) == ceil(8/5) == 2, so host 1
    # owns units[2:4] under BOTH fleet sizes — its stream must not change
    a = _stream(dataset, loader_kw={"shard": (1, 4)}, batch=64)
    b = _stream(dataset, loader_kw={"shard": (1, 5)}, batch=64)
    assert a == b and len(a) > 0


def test_empty_shard_is_a_valid_noop_loader(dataset):
    # 8 units, host_count=11 -> k=1: hosts 8..10 own nothing
    with DataLoader(dataset, 64, shard=(9, 11), num_epochs=1) as ld:
        assert ld.batches_per_epoch == 0
        assert list(ld) == []


def test_drop_remainder_and_padding(dataset):
    with trace.scope() as t:
        with DataLoader(dataset, 256, num_epochs=1,
                        drop_remainder=True) as ld:
            batches = list(ld)
            rows = ld.rows_per_epoch
    assert len(batches) == rows // 256
    assert all(b.num_valid == 256 and b.row_mask is None for b in batches)
    # the dropped tail is ACCOUNTED, never silent: emitted + dropped
    # add back up to the epoch's real rows
    assert rows % 256 > 0  # the fixture must exercise a real remainder
    assert t.counters().get("data.rows_dropped") == rows % 256
    assert t.counters()["data.rows_emitted"] + rows % 256 == rows
    with DataLoader(dataset, 256, num_epochs=1, drop_remainder=False) as ld:
        padded = list(ld)
    assert len(padded) == -(-rows // 256)
    tail = padded[-1]
    assert tail.num_valid == rows - 256 * (len(padded) - 1)
    assert np.asarray(tail.row_mask).sum() == tail.num_valid


# ---------------------------------------------------------------------------
# loader: checkpoint / resume
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("at", [1, 7, 23])
def test_host_resume_is_bit_identical(dataset, at):
    full = _stream(dataset)
    assert _stream(dataset, restore_at=at) == full[at:]


def test_host_resume_across_the_epoch_boundary(dataset):
    full = _stream(dataset)
    per_epoch = len(full) // 2
    at = per_epoch + 3  # a batch index inside epoch 1
    assert _stream(dataset, restore_at=at) == full[at:]


def test_restore_rejects_mismatched_configuration(dataset):
    with DataLoader(dataset, 256, shuffle_seed=7, num_epochs=1) as ld:
        state = ld.state()
    with DataLoader(dataset, 128, shuffle_seed=7, num_epochs=1) as other:
        with pytest.raises(ValueError, match="batch_size"):
            other.restore(state)
    with DataLoader(dataset, 256, shuffle_seed=8, num_epochs=1) as other:
        with pytest.raises(ValueError, match="shuffle_seed"):
            other.restore(state)
    with DataLoader(dataset, 256, shuffle_seed=7, num_epochs=1) as same:
        with pytest.raises(ValueError, match="version"):
            same.restore({**state, "version": 99})
        with pytest.raises(ValueError, match="outside"):
            same.restore({**state, "batch": 10_000})
        same.restore(state)  # the matching configuration restores fine


def test_state_is_json_serializable(dataset):
    with DataLoader(dataset, 256, shuffle_seed=7, shuffle_window=512,
                    num_epochs=2) as ld:
        next(iter(ld))
        state = ld.state()
    rt = json.loads(json.dumps(state))
    assert rt == state
    assert state["epoch"] == 0 and state["batch"] == 1


# ---------------------------------------------------------------------------
# loader: device face
# ---------------------------------------------------------------------------


def test_device_stream_is_deterministic(dataset):
    s1 = _stream(dataset, engine="tpu", num_epochs=1,
                 loader_kw={"float64_policy": "float64"})
    s2 = _stream(dataset, engine="tpu", num_epochs=1,
                 loader_kw={"float64_policy": "float64"})
    assert s1 == s2 and len(s1) > 10


def test_device_stream_matches_host_values(dataset):
    host = _stream(dataset, num_epochs=1)
    dev = _stream(dataset, engine="tpu", num_epochs=1,
                  loader_kw={"float64_policy": "float64"})
    assert dev == host


@pytest.mark.parametrize("at", [3, 19])
def test_device_resume_is_bit_identical(dataset, at):
    kw = dict(engine="tpu", loader_kw={"float64_policy": "float64"})
    full = _stream(dataset, **kw)
    assert _stream(dataset, restore_at=at, **kw) == full[at:]


def test_device_batches_are_jax_arrays(dataset):
    import jax

    with DataLoader(dataset, 256, shuffle_seed=7, num_epochs=1,
                    engine="tpu") as ld:
        b = next(iter(ld))
    assert isinstance(b.columns[0].values, jax.Array)
    assert b.column("k").values.shape == (256,)


# ---------------------------------------------------------------------------
# loader: validation and edge cases
# ---------------------------------------------------------------------------


def test_constructor_validation(dataset):
    with pytest.raises(ValueError, match="batch_size"):
        DataLoader(dataset, 0)
    with pytest.raises(ValueError, match="engine"):
        DataLoader(dataset, 8, engine="gpu")
    with pytest.raises(ValueError, match="num_epochs"):
        DataLoader(dataset, 8, num_epochs=0)
    with pytest.raises(ValueError, match="shuffle_window"):
        DataLoader(dataset, 8, shuffle_window=-1)
    with pytest.raises(ValueError, match="shuffle_seed"):
        DataLoader(dataset, 8, shuffle_window=64)  # window without seed
    with pytest.raises(ValueError, match="at least one source"):
        DataLoader([], 8)
    with pytest.raises(UnsupportedFeatureError, match="verify_crc"):
        DataLoader(dataset, 8, engine="tpu",
                   reader_options=ReaderOptions(verify_crc=True))
    # salvage is HONORED on both faces now (tests/test_data salvage
    # section), including verify_crc+salvage on the device face (the
    # unit decode is delegated to the host engine, which runs the CRC)
    DataLoader(dataset, 8, engine="tpu", reader_options=ReaderOptions(
        verify_crc=True, salvage=True,
    )).close()
    with pytest.raises(ValueError, match="selects nothing"):
        DataLoader(dataset, 8, columns=["nope"])


def test_verify_crc_allowed_on_the_host_face(dataset):
    ref = _stream(dataset, num_epochs=1)
    crc = _stream(dataset, num_epochs=1, loader_kw={
        "reader_options": ReaderOptions(verify_crc=True),
    })
    assert crc == ref


def test_repeated_columns_rejected(tmp_path):
    from parquet_floor_tpu import ParquetFileWriter, types

    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.repeated(types.INT32).named("tags"),
    )
    p = str(tmp_path / "rep.parquet")
    with ParquetFileWriter(p, schema) as w:
        w.write_columns({"k": [1, 2], "tags": [[1, 2], [3]]})
    with pytest.raises(UnsupportedFeatureError, match="repeated"):
        DataLoader([p], 2)
    # projecting the repeated column away makes the file loadable
    with DataLoader([p], 2, columns=["k"], num_epochs=1,
                    drop_remainder=False) as ld:
        (b,) = list(ld)
        assert np.array_equal(np.asarray(b.column("k").values)[:2], [1, 2])


def test_columns_projection(dataset):
    with DataLoader(dataset, 128, columns=["k", "s"], shuffle_seed=3,
                    num_epochs=1) as ld:
        b = next(iter(ld))
    assert [c.descriptor.path[0] for c in b.columns] == ["k", "s"]


def test_closed_loader_stops(dataset):
    ld = DataLoader(dataset, 128, num_epochs=1)
    next(iter(ld))
    ld.close()
    ld.close()  # idempotent
    with pytest.raises(StopIteration):
        next(iter(ld))


def test_factory_sources_reopen_per_epoch(dataset):
    opens = []

    def factory(path):
        def make():
            opens.append(path)
            from parquet_floor_tpu.io.source import FileSource

            return FileSource(path)
        return make

    ref = _stream(dataset, num_epochs=2)
    got = _stream([factory(p) for p in dataset], num_epochs=2)
    assert got == ref
    assert len(opens) >= len(dataset)  # footer pass + each epoch's reads


# ---------------------------------------------------------------------------
# fault injection: transient retries never perturb the stream
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("engine", ["host", "tpu"])
def test_transient_faults_do_not_perturb_order(dataset, engine):
    kw = {} if engine == "host" else {"float64_policy": "float64"}
    ref = _stream(dataset, engine=engine, num_epochs=1, loader_kw=kw)

    def faulty(path, seed):
        def make():
            return FaultInjectingSource(
                path, seed=seed, transient_error_rate=0.05,
                max_transient_failures=8,
            )
        return make

    got = _stream(
        [faulty(p, i) for i, p in enumerate(dataset)],
        engine=engine, num_epochs=1,
        loader_kw={**kw, "reader_options": ReaderOptions(io_retries=16)},
    )
    assert got == ref


# ---------------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------------


def test_epoch_reports_and_merged_summary(dataset):
    with trace.scope() as t:
        with DataLoader(dataset, 256, shuffle_seed=7, num_epochs=2,
                        drop_remainder=False) as ld:
            n = sum(1 for _ in ld)
            reports = ld.epoch_reports
            merged = ld.report()
    assert len(reports) == 2
    per_epoch = ld.rows_per_epoch
    for rep in reports:
        assert rep.counters.get("data.rows_emitted") == per_epoch
        assert rep.wall_seconds and rep.wall_seconds > 0
    assert merged.counters["data.rows_emitted"] == 2 * per_epoch
    assert merged.counters["data.batches_emitted"] == n
    assert t.counters()["data.epochs_completed"] == 2


def test_epoch_report_gauges_are_per_epoch(dataset):
    """Gauges must come from the epoch's own window, not the cumulative
    tracer maxima: an epoch whose peak is below the run's never moves
    the cumulative gauge, so inheriting it would attribute epoch 0's
    high-water marks to every later epoch (and bleed in any other scan
    sharing the tracer)."""
    with trace.scope() as t:
        # a foreign scan's high-water mark, recorded BEFORE the loader
        t.gauge_max("scan.inflight_bytes_max", 1 << 40)
        with DataLoader(dataset, 256, shuffle_seed=7, num_epochs=2,
                        drop_remainder=False) as ld:
            for _ in ld:
                pass
            reports = ld.epoch_reports
    assert len(reports) == 2
    for rep in reports:
        # the foreign peak stays out of every epoch's report...
        assert rep.gauges.get("scan.inflight_bytes_max", 0) < (1 << 40)
    # ...while the cumulative tracer still holds it
    assert t.gauges()["scan.inflight_bytes_max"] == 1 << 40


def test_gauge_window_isolation():
    """The trace-level contract behind per-epoch gauges: a window sees
    only writes made while it is open; close() detaches it."""
    t = trace.Tracer(enabled=True)
    t.gauge_max("scan.queue_depth_max", 100)
    w = t.gauge_window()
    t.gauge_max("scan.queue_depth_max", 7)
    assert w.gauges() == {"scan.queue_depth_max": 7}   # not the prior 100
    assert w.close() == {"scan.queue_depth_max": 7}
    t.gauge_max("scan.queue_depth_max", 500)           # after close: unseen
    assert w.gauges() == {"scan.queue_depth_max": 7}
    assert t.gauges()["scan.queue_depth_max"] == 500   # cumulative intact
    w.close()                                          # idempotent


def test_scan_report_merge_round_trips_through_dicts(dataset):
    """The cross-process contract: per-host reports ship as_dict() JSON
    and the coordinator rebuilds + merges them."""
    def host_report(shard):
        with trace.scope():
            with DataLoader(dataset, 64, shuffle_seed=1, num_epochs=1,
                            shard=shard) as ld:
                for _ in ld:
                    pass
                return ld.report()

    reports = [host_report((h, 2)) for h in range(2)]
    wire = [json.loads(json.dumps(r.as_dict())) for r in reports]
    rebuilt = [trace.ScanReport.from_dict(d) for d in wire]
    merged = trace.ScanReport.merge(rebuilt)
    total = sum(r.counters["data.rows_emitted"] for r in reports)
    assert merged.counters["data.rows_emitted"] == total
    # as_dict() rounds wall_seconds for the wire; merge takes the max
    assert merged.wall_seconds == pytest.approx(
        max(r.wall_seconds for r in reports), abs=1e-6
    )
    with pytest.raises(ValueError):
        trace.ScanReport.merge([])
    with pytest.raises(ValueError, match="unknown keys"):
        trace.ScanReport.from_dict({"bogus": 1})


# ---------------------------------------------------------------------------
# scanner order mode + windowed engine iterator (the loader's plumbing)
# ---------------------------------------------------------------------------


def test_scanner_order_mode_delivers_the_permutation(dataset):
    seq = {}
    with DatasetScanner(dataset, columns=["k"]) as sc:
        for u in sc:
            seq[(u.file_index, u.group_index)] = np.asarray(
                u.batch.columns[0].values
            )
    order = [(3, 1), (0, 0), (2, 1), (0, 1), (1, 0)]
    got = []
    with DatasetScanner(dataset, columns=["k"], order=order) as sc:
        for u in sc:
            got.append((u.file_index, u.group_index))
            assert np.array_equal(
                np.asarray(u.batch.columns[0].values),
                seq[(u.file_index, u.group_index)],
            )
    assert got == order


def test_scanner_order_mode_validation(dataset):
    with pytest.raises(ValueError, match="twice"):
        # constructor raises before any file opens: nothing to release
        DatasetScanner(dataset, order=[(0, 0), (0, 0)])  # floorlint: disable=FL-RES001
    with pytest.raises(ValueError, match="outside"):
        DatasetScanner(dataset, order=[(9, 0)])  # floorlint: disable=FL-RES001
    with DatasetScanner(dataset, order=[(0, 7)]) as sc:
        with pytest.raises(ValueError, match="outside file"):
            list(sc)


def test_scanner_order_mode_windows_file_lifetimes(dataset):
    """In order mode a file opens at its first ordered unit and closes
    after its last one — fd usage follows the order, not the dataset."""
    from parquet_floor_tpu.io.source import FileSource

    live = set()

    class Tracked(FileSource):
        def __init__(self, path):
            super().__init__(path)
            live.add(self)

        def close(self):
            live.discard(self)
            super().close()

    high_water = 0
    order = [(0, 0), (0, 1), (1, 0), (1, 1), (2, 0), (2, 1)]
    with DatasetScanner(
        [lambda p=p: Tracked(p) for p in dataset[:3]],
        columns=["k"], order=order,
        scan=ScanOptions(threads=1),
    ) as sc:
        for _ in sc:
            high_water = max(high_water, len(live))
    assert not live
    # strictly fewer than all 3 files ever open at once (the scheduler
    # prefetches ahead, so exactly-one is not guaranteed; all-at-once
    # would mean windowing is broken)
    assert high_water < 3


def test_windowed_engine_iterator_matches_eager(dataset):
    from parquet_floor_tpu.tpu.engine import (
        TpuRowGroupReader,
        iter_dataset_row_groups,
    )

    readers = [
        TpuRowGroupReader(ParquetFileReader(p), float64_policy="float64")
        for p in dataset[:3]
    ]
    tasks = [(readers[0], 0), (readers[1], 1), (readers[0], 1),
             (readers[2], 0)]
    eager = [
        {k: np.asarray(v.values) for k, v in cols.items()}
        for cols in iter_dataset_row_groups(list(tasks), columns=["k", "d"])
    ]

    closed = []
    lazy_readers = {}

    def opener(fi):
        def open_():
            r = lazy_readers.get(fi)
            if r is None:
                r = lazy_readers[fi] = TpuRowGroupReader(
                    ParquetFileReader(dataset[fi]),
                    float64_policy="float64",
                )
            return r
        return open_

    def stream():
        yield (opener(0), 0, False)
        yield (opener(1), 1, True)
        yield (opener(0), 1, True)
        yield (opener(2), 0, True)

    windowed = []
    for cols in iter_dataset_row_groups(stream(), columns=["k", "d"]):
        windowed.append({k: np.asarray(v.values) for k, v in cols.items()})
    for r in readers:
        r.close()
    assert len(windowed) == len(eager)
    for a, b in zip(eager, windowed):
        assert a.keys() == b.keys()
        for k in a:
            assert np.array_equal(a[k], b[k], equal_nan=True)
    # close_after really closed the pipeline-owned readers
    assert all(r.reader._closed for r in lazy_readers.values())


def test_windowed_engine_iterator_closes_on_abandonment(dataset):
    from parquet_floor_tpu.tpu.engine import (
        TpuRowGroupReader,
        iter_dataset_row_groups,
    )

    opened = []

    def opener(fi):
        def open_():
            r = TpuRowGroupReader(ParquetFileReader(dataset[fi]))
            opened.append(r)
            return r
        return open_

    def stream():
        for fi in range(4):
            yield (opener(fi), 0, False)
            yield (opener(fi), 1, True)

    gen = iter_dataset_row_groups(stream(), columns=["k"])
    next(gen)
    gen.close()  # abandon mid-stream
    assert opened  # the pipeline really opened ahead
    assert all(r.reader._closed for r in opened)


# ---------------------------------------------------------------------------
# salvage: unit quarantine, checkpoint semantics, resume bit-identity
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def damaged_dataset(dataset, tmp_path_factory):
    """The scan fixture's 4-file dataset with file 1 / group 1's
    REQUIRED ``k`` chunk framing-damaged: geometry-changing loss the
    loader must quarantine at the unit level."""
    from tests.test_scan import _break_required_chunk

    d = tmp_path_factory.mktemp("data_salvage")
    paths = list(dataset)
    paths[1] = _break_required_chunk(dataset[1], d, 1, "k", "loader_q")
    return paths


_SALV = {"reader_options": ReaderOptions(salvage=True)}


def _clean_minus_unit(dataset, damaged_paths, batch=256):
    """The expected surviving stream: the clean dataset with file 1 /
    group 1's rows removed — streamed through a salvage loader over a
    dataset where that unit is ALREADY known-quarantined, which plans it
    at zero rows from batch one."""
    ld = DataLoader(dataset, batch, shuffle_seed=7, shuffle_window=512,
                    num_epochs=2, drop_remainder=False, **_SALV)
    try:
        state = ld.state()
        state["quarantined"] = [[1, 1]]
        restored = DataLoader(
            damaged_paths, batch, shuffle_seed=7, shuffle_window=512,
            num_epochs=2, drop_remainder=False, **_SALV,
        ).restore(state)
        out = [_batch_bytes(b) for b in restored]
        restored.close()
    finally:
        ld.close()
    return out


def test_loader_salvage_quarantines_geometry_damaged_unit(damaged_dataset):
    """The host face drops the damaged unit WHOLE, keeps flowing,
    records the quarantine (state + report + counters), and the
    surviving multiset is exactly the clean data minus that unit."""
    with trace.scope() as t:
        ld = DataLoader(damaged_dataset, 256, shuffle_seed=7,
                        shuffle_window=512, num_epochs=1,
                        drop_remainder=False, **_SALV)
        ks = []
        for b in ld:
            ks.append(np.asarray(b.column("k").values)[: b.num_valid])
        assert ld.quarantined_units == [(1, 1)]
        rep = ld.salvage_report
        assert rep is not None and rep.chunks_quarantined == 1
        assert [s.key() for s in rep.skips] == [(1, "k", None, "chunk")]
        assert t.counters().get("data.units_quarantined") == 1
        state = ld.state()
        assert state["quarantined"] == [[1, 1]]
        ld.close()

    got = np.sort(np.concatenate(ks))
    # clean reference: every unit except (1, 1)
    want = []
    from tests.test_scan import _seq_units

    for fi, gi, g in _seq_units(
        [p for i, p in enumerate(damaged_dataset) if i != 1]
    ):
        want.append(np.asarray(
            [c for c in g.columns
             if c.descriptor.path[0] == "k"][0].values
        ))
    with ParquetFileReader(damaged_dataset[1],
                           options=ReaderOptions(salvage=True)) as r:
        g0 = r.read_row_group(0)
        want.append(np.asarray(
            [c for c in g0.columns
             if c.descriptor.path[0] == "k"][0].values
        ))
    assert np.array_equal(got, np.sort(np.concatenate(want)))


def test_loader_salvage_page_null_damage_flows_through(dataset,
                                                       tmp_path_factory):
    """Page-null damage (flat OPTIONAL column) keeps geometry: no unit
    quarantined, identical row count, the damaged span arrives as
    masked nulls — only the mask differs from the clean stream."""
    from tests.test_salvage import _flip_in_page

    d = tmp_path_factory.mktemp("data_pnull")
    paths = list(dataset)
    paths[2], _ = _flip_in_page(dataset[2], d, 0, "d", 1, "loader_pn")

    kw = {"reader_options": ReaderOptions(salvage=True, verify_crc=True)}
    ld = DataLoader(paths, 256, num_epochs=1, drop_remainder=False, **kw)
    n_rows = 0
    for b in ld:
        n_rows += b.num_valid
    assert ld.quarantined_units == []
    rep = ld.salvage_report
    assert rep.pages_skipped == 1 and rep.chunks_quarantined == 0
    assert [s.kind for s in rep.skips] == ["page_null"]
    assert n_rows == ld.rows_per_epoch == 4 * 3000
    ld.close()


def _first_quarantine_batch(paths, batch=256):
    """The 1-indexed batch count after which the damaged unit first
    shows up in checkpoint state (deterministic for a fixed seed)."""
    ld = DataLoader(paths, batch, shuffle_seed=7, shuffle_window=512,
                    num_epochs=2, **_SALV)
    it = iter(ld)
    k = 0
    try:
        while not ld.state()["quarantined"]:
            next(it)
            k += 1
    finally:
        ld.close()
    return k


@pytest.mark.parametrize("side", ["before", "after"])
def test_host_resume_bit_identical_under_quarantine(damaged_dataset, side):
    """The satellite's acceptance case: a quarantined unit BEFORE the
    resume point (the restored loader must replay the shrunken plan,
    not re-discover) and AFTER it (the restored loader must re-discover
    at the same position) — both resume bit-identically."""
    full = _stream(damaged_dataset, loader_kw=_SALV)
    k = _first_quarantine_batch(damaged_dataset)
    at = k + 2 if side == "before" else max(1, k - 1)
    assert _stream(damaged_dataset, restore_at=at,
                   loader_kw=_SALV) == full[at:]


def test_host_resume_under_quarantine_across_epoch_boundary(damaged_dataset):
    full = _stream(damaged_dataset, loader_kw=_SALV)
    per_epoch = len(full) // 2
    at = per_epoch + 2
    assert _stream(damaged_dataset, restore_at=at,
                   loader_kw=_SALV) == full[at:]


def test_quarantine_shrinks_the_stream_to_the_surviving_rows(
    damaged_dataset, dataset
):
    """After the quarantine is discovered, every later epoch plans the
    unit at zero rows: epoch 1 of the damaged run equals epoch 1 of a
    run that KNEW the quarantine from batch one (same plan keying)."""
    full = _stream(damaged_dataset, loader_kw=_SALV)
    known = _clean_minus_unit(dataset, damaged_dataset)
    # identical from batch one: skipping the unit at delivery (full) and
    # planning it at zero rows (known) produce the same stream, because
    # unit order and per-position window perms are independent of the
    # quarantined unit's row count
    assert full == known


def test_device_loader_salvage_matches_host(damaged_dataset):
    """The device face quarantines the same unit and emits the same
    surviving bytes as the host face (mirrors
    test_device_stream_matches_host_values)."""
    kw = {**_SALV, "float64_policy": "float64"}
    host = _stream(damaged_dataset, engine="host", num_epochs=1,
                   loader_kw=kw)
    dev = _stream(damaged_dataset, engine="tpu", num_epochs=1,
                  loader_kw=kw)
    assert dev == host


def test_device_resume_bit_identical_under_quarantine(damaged_dataset):
    kw = {**_SALV, "float64_policy": "float64"}
    full = _stream(damaged_dataset, engine="tpu", loader_kw=kw)
    k = _first_quarantine_batch(damaged_dataset)
    at = k + 2
    assert _stream(damaged_dataset, engine="tpu", restore_at=at,
                   loader_kw=kw) == full[at:]


def test_restore_rejects_quarantine_state_without_salvage(damaged_dataset):
    ld = DataLoader(damaged_dataset, 256, num_epochs=1, **_SALV)
    for _ in zip(range(100), ld):
        pass
    state = ld.state()
    ld.close()
    assert state["quarantined"] == [[1, 1]]
    with DataLoader(damaged_dataset, 256, num_epochs=1) as strict:
        with pytest.raises(ValueError, match="salvage"):
            strict.restore(state)
    state["quarantined"] = [[9, 9]]
    with DataLoader(damaged_dataset, 256, num_epochs=1, **_SALV) as other:
        with pytest.raises(ValueError, match="unknown units"):
            other.restore(state)


def test_salvage_report_merge_is_associative_across_threads(damaged_dataset):
    """The merge protocol's load-bearing property: per-unit reports
    produced by CONCURRENT worker decodes fold to the same dataset
    report no matter how sub-merges group — ((a·b)·c) == (a·(b·c)) ==
    merge([a, b, c]) — so worker-local pre-folds compose."""
    from concurrent.futures import ThreadPoolExecutor

    from parquet_floor_tpu.format.file_read import SalvageReport

    def unit_reports():
        with DatasetScanner(
            damaged_dataset, options=ReaderOptions(salvage=True)
        ) as sc:
            return [u.salvage for u in sc]

    with ThreadPoolExecutor(max_workers=3) as pool:
        reports = list(pool.map(
            lambda _: unit_reports(), range(3)
        ))

    for reps in reports:
        assert any(r.skips for r in reps)
        flat = SalvageReport.merge(reps)
        left = SalvageReport.merge(
            [SalvageReport.merge(reps[:4]), SalvageReport.merge(reps[4:])]
        )
        right = SalvageReport.merge(
            [reps[0], SalvageReport.merge(reps[1:])]
        )
        for other in (left, right):
            assert other.as_dict() == flat.as_dict()
            assert [s.key() for s in other.skips] == \
                [s.key() for s in flat.skips]
    # concurrency never perturbs the fold: every thread's dataset
    # report is identical
    assert all(
        SalvageReport.merge(r).as_dict() ==
        SalvageReport.merge(reports[0]).as_dict()
        for r in reports
    )


# ---------------------------------------------------------------------------
# device double-buffering: prefetch_to_device (docs/perf.md)
# ---------------------------------------------------------------------------


def _prefetch_stream(paths, engine, depth, **kw):
    kw.setdefault("shuffle_seed", 7)
    kw.setdefault("shuffle_window", 512)
    kw.setdefault("num_epochs", 2)
    kw.setdefault("drop_remainder", False)
    ld = DataLoader(paths, 256, engine=engine, **kw)
    out = [_batch_bytes(b) for b in ld.prefetch_to_device(depth)]
    ld.close()
    return out


@pytest.mark.parametrize("engine", ["host", "tpu"])
@pytest.mark.parametrize("depth", [1, 2, 3])
def test_prefetch_to_device_stream_is_identical(dataset, engine, depth):
    """Double-buffering reorders WHEN work happens, never what comes
    out: the prefetched stream is bit-identical to plain iteration."""
    assert _prefetch_stream(dataset, engine, depth) == \
        _stream(dataset, engine=engine)


def test_prefetch_to_device_counters(dataset):
    with trace.scope() as t:
        ld = DataLoader(dataset, 256, shuffle_seed=7, num_epochs=1,
                        drop_remainder=False, engine="host")
        n = sum(1 for _ in ld.prefetch_to_device(3))
        ld.close()
    c = t.counters()
    assert c.get("data.prefetch_to_device_batches") == n
    assert 1 <= t.gauges().get("data.prefetch_to_device_depth_max", 0) <= 3


@pytest.mark.parametrize("at", [0, 1, 3, 7])
def test_prefetch_state_resumes_at_the_consumed_batch(dataset, at):
    """The prefetcher's state() reflects the last batch the CONSUMER
    saw, not the pulled-ahead loader position: restoring it replays the
    buffered batches too, bit-identical to the uninterrupted run."""
    ref = _stream(dataset, engine="host")
    ld = DataLoader(dataset, 256, shuffle_seed=7, shuffle_window=512,
                    num_epochs=2, drop_remainder=False, engine="host")
    pf = ld.prefetch_to_device(3)
    head = [_batch_bytes(next(pf)) for _ in range(at)]
    state = json.loads(json.dumps(pf.state()))
    ld.close()
    ld2 = DataLoader(dataset, 256, shuffle_seed=7, shuffle_window=512,
                     num_epochs=2, drop_remainder=False,
                     engine="host").restore(state)
    tail = [_batch_bytes(b) for b in ld2]
    ld2.close()
    assert head + tail == ref


def test_prefetch_device_batches_stay_jax_arrays(dataset):
    import jax

    ld = DataLoader(dataset, 256, shuffle_seed=3, num_epochs=1,
                    engine="tpu", float64_policy="bits")
    pf = ld.prefetch_to_device(2)
    b = next(pf)
    assert all(isinstance(c.values, jax.Array) for c in b.columns)
    ld.close()


def test_prefetch_depth_validation(dataset):
    ld = DataLoader(dataset, 256, num_epochs=1)
    with pytest.raises(ValueError):
        ld.prefetch_to_device(0)
    ld.close()
