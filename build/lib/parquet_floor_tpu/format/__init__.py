"""L2: from-scratch Parquet format engine (SURVEY.md §7 `format/`)."""
