"""File metadata: footer parse/serialize + user-facing ParquetMetadata.

Parity with the metadata surface the reference exposes raw
(``ParquetReader.readMetadata`` at ``ParquetReader.java:109-117`` and
``metaData()`` at ``:229-231``): file-level schema, created_by, row groups,
column-chunk stats.

Layout (Parquet spec): ``PAR1 ... footer-thrift footer-len:u32le PAR1``.
"""

from __future__ import annotations

from typing import List, Optional

from ..io.source import FileSource
from .parquet_thrift import FileMetaData, RowGroup
from .schema import MessageType
from .thrift import CompactReader, CompactWriter

MAGIC = b"PAR1"
MAGIC_ENCRYPTED = b"PARE"
FOOTER_TAIL = 8  # u32 length + magic


class ParquetMetadata:
    """Parsed footer: raw thrift + derived schema tree."""

    __slots__ = ("file_meta", "schema")

    def __init__(self, file_meta: FileMetaData):
        self.file_meta = file_meta
        self.schema: MessageType = MessageType.from_thrift(file_meta.schema or [])

    @property
    def num_rows(self) -> int:
        return self.file_meta.num_rows or 0

    @property
    def created_by(self) -> Optional[str]:
        return self.file_meta.created_by

    @property
    def row_groups(self) -> List[RowGroup]:
        return self.file_meta.row_groups or []

    @property
    def key_value_metadata(self) -> dict:
        kvs = self.file_meta.key_value_metadata or []
        return {kv.key: kv.value for kv in kvs}

    def __repr__(self):
        return (
            f"ParquetMetadata(rows={self.num_rows}, "
            f"row_groups={len(self.row_groups)}, created_by={self.created_by!r})"
        )


def read_footer(source: FileSource) -> ParquetMetadata:
    size = source.size
    if size < len(MAGIC) + FOOTER_TAIL:
        raise ValueError(f"not a parquet file: only {size} bytes")
    head = bytes(source.read_at(0, 4))
    tail = bytes(source.read_at(size - FOOTER_TAIL, FOOTER_TAIL))
    if tail[4:] == MAGIC_ENCRYPTED:
        raise ValueError("encrypted parquet files are not supported")
    if head != MAGIC or tail[4:] != MAGIC:
        raise ValueError("not a parquet file: bad magic")
    footer_len = int.from_bytes(tail[:4], "little")
    if footer_len + FOOTER_TAIL + len(MAGIC) > size:
        raise ValueError(f"corrupt footer length {footer_len}")
    footer_bytes = source.read_at(size - FOOTER_TAIL - footer_len, footer_len)
    fm = FileMetaData.read(CompactReader(footer_bytes))
    return ParquetMetadata(fm)


def serialize_footer(file_meta: FileMetaData) -> bytes:
    w = CompactWriter()
    file_meta.write(w)
    body = w.getvalue()
    return body + len(body).to_bytes(4, "little") + MAGIC
