"""Parquet metadata structures (parquet.thrift), declared over the compact
protocol layer in :mod:`parquet_floor_tpu.format.thrift`.

These mirror the Apache Parquet format specification's ``parquet.thrift``
(the same structures parquet-mr 1.12.2 serializes for the reference — see
SURVEY.md §2.3; footer write exercised at reference ``ParquetWriter.java:74-77``,
footer read at ``ParquetReader.java:114-120``).  Field ids and enum values are
fixed by the public format spec.
"""

from __future__ import annotations

from .thrift import (
    T_BOOL,
    T_BYTE,
    T_I16,
    T_I32,
    T_I64,
    T_BINARY,
    T_STRING,
    TList,
    ThriftStruct,
)


# ---------------------------------------------------------------------------
# Enums (plain int namespaces; wire values fixed by the format spec)
# ---------------------------------------------------------------------------

class Type:
    """Physical types."""

    BOOLEAN = 0
    INT32 = 1
    INT64 = 2
    INT96 = 3
    FLOAT = 4
    DOUBLE = 5
    BYTE_ARRAY = 6
    FIXED_LEN_BYTE_ARRAY = 7

    _NAMES = {
        0: "BOOLEAN", 1: "INT32", 2: "INT64", 3: "INT96",
        4: "FLOAT", 5: "DOUBLE", 6: "BYTE_ARRAY", 7: "FIXED_LEN_BYTE_ARRAY",
    }

    @classmethod
    def name(cls, v):
        return cls._NAMES.get(v, f"UNKNOWN({v})")


class ConvertedType:
    UTF8 = 0
    MAP = 1
    MAP_KEY_VALUE = 2
    LIST = 3
    ENUM = 4
    DECIMAL = 5
    DATE = 6
    TIME_MILLIS = 7
    TIME_MICROS = 8
    TIMESTAMP_MILLIS = 9
    TIMESTAMP_MICROS = 10
    UINT_8 = 11
    UINT_16 = 12
    UINT_32 = 13
    UINT_64 = 14
    INT_8 = 15
    INT_16 = 16
    INT_32 = 17
    INT_64 = 18
    JSON = 19
    BSON = 20
    INTERVAL = 21


class FieldRepetitionType:
    REQUIRED = 0
    OPTIONAL = 1
    REPEATED = 2

    _NAMES = {0: "REQUIRED", 1: "OPTIONAL", 2: "REPEATED"}

    @classmethod
    def name(cls, v):
        return cls._NAMES.get(v, f"UNKNOWN({v})")


class Encoding:
    PLAIN = 0
    PLAIN_DICTIONARY = 2
    RLE = 3
    BIT_PACKED = 4
    DELTA_BINARY_PACKED = 5
    DELTA_LENGTH_BYTE_ARRAY = 6
    DELTA_BYTE_ARRAY = 7
    RLE_DICTIONARY = 8
    BYTE_STREAM_SPLIT = 9

    _NAMES = {
        0: "PLAIN", 2: "PLAIN_DICTIONARY", 3: "RLE", 4: "BIT_PACKED",
        5: "DELTA_BINARY_PACKED", 6: "DELTA_LENGTH_BYTE_ARRAY",
        7: "DELTA_BYTE_ARRAY", 8: "RLE_DICTIONARY", 9: "BYTE_STREAM_SPLIT",
    }

    @classmethod
    def name(cls, v):
        return cls._NAMES.get(v, f"UNKNOWN({v})")


class CompressionCodec:
    UNCOMPRESSED = 0
    SNAPPY = 1
    GZIP = 2
    LZO = 3
    BROTLI = 4
    LZ4 = 5
    ZSTD = 6
    LZ4_RAW = 7

    _NAMES = {
        0: "UNCOMPRESSED", 1: "SNAPPY", 2: "GZIP", 3: "LZO",
        4: "BROTLI", 5: "LZ4", 6: "ZSTD", 7: "LZ4_RAW",
    }

    @classmethod
    def name(cls, v):
        return cls._NAMES.get(v, f"UNKNOWN({v})")


class PageType:
    DATA_PAGE = 0
    INDEX_PAGE = 1
    DICTIONARY_PAGE = 2
    DATA_PAGE_V2 = 3


class BoundaryOrder:
    UNORDERED = 0
    ASCENDING = 1
    DESCENDING = 2


# ---------------------------------------------------------------------------
# Logical types (union of empty/parameter structs)
# ---------------------------------------------------------------------------

class StringType(ThriftStruct):
    FIELDS = {}


class UUIDType(ThriftStruct):
    FIELDS = {}


class MapType(ThriftStruct):
    FIELDS = {}


class ListType(ThriftStruct):
    FIELDS = {}


class EnumType(ThriftStruct):
    FIELDS = {}


class DateType(ThriftStruct):
    FIELDS = {}


class NullType(ThriftStruct):
    FIELDS = {}


class JsonType(ThriftStruct):
    FIELDS = {}


class BsonType(ThriftStruct):
    FIELDS = {}


class Float16Type(ThriftStruct):
    FIELDS = {}


class DecimalType(ThriftStruct):
    FIELDS = {1: ("scale", T_I32), 2: ("precision", T_I32)}


class MilliSeconds(ThriftStruct):
    FIELDS = {}


class MicroSeconds(ThriftStruct):
    FIELDS = {}


class NanoSeconds(ThriftStruct):
    FIELDS = {}


class TimeUnit(ThriftStruct):
    """Union: exactly one of the members is set."""

    FIELDS = {
        1: ("MILLIS", MilliSeconds),
        2: ("MICROS", MicroSeconds),
        3: ("NANOS", NanoSeconds),
    }


class TimestampType(ThriftStruct):
    FIELDS = {1: ("isAdjustedToUTC", T_BOOL), 2: ("unit", TimeUnit)}


class TimeType(ThriftStruct):
    FIELDS = {1: ("isAdjustedToUTC", T_BOOL), 2: ("unit", TimeUnit)}


class IntType(ThriftStruct):
    FIELDS = {1: ("bitWidth", T_BYTE), 2: ("isSigned", T_BOOL)}


class LogicalType(ThriftStruct):
    """Union: exactly one member set (parquet.thrift LogicalType)."""

    FIELDS = {
        1: ("STRING", StringType),
        2: ("MAP", MapType),
        3: ("LIST", ListType),
        4: ("ENUM", EnumType),
        5: ("DECIMAL", DecimalType),
        6: ("DATE", DateType),
        7: ("TIME", TimeType),
        8: ("TIMESTAMP", TimestampType),
        10: ("INTEGER", IntType),
        11: ("UNKNOWN", NullType),
        12: ("JSON", JsonType),
        13: ("BSON", BsonType),
        14: ("UUID", UUIDType),
        15: ("FLOAT16", Float16Type),
    }

    def set_member(self):
        """Return (name, value) of the set union member, or (None, None)."""
        for name, _ in self.FIELDS.values():
            v = getattr(self, name)
            if v is not None:
                return name, v
        return None, None


# ---------------------------------------------------------------------------
# Schema / statistics / pages
# ---------------------------------------------------------------------------

class SchemaElement(ThriftStruct):
    FIELDS = {
        1: ("type", T_I32),
        2: ("type_length", T_I32),
        3: ("repetition_type", T_I32),
        4: ("name", T_STRING),
        5: ("num_children", T_I32),
        6: ("converted_type", T_I32),
        7: ("scale", T_I32),
        8: ("precision", T_I32),
        9: ("field_id", T_I32),
        10: ("logicalType", LogicalType),
    }


class Statistics(ThriftStruct):
    FIELDS = {
        1: ("max", T_BINARY),
        2: ("min", T_BINARY),
        3: ("null_count", T_I64),
        4: ("distinct_count", T_I64),
        5: ("max_value", T_BINARY),
        6: ("min_value", T_BINARY),
        7: ("is_max_value_exact", T_BOOL),
        8: ("is_min_value_exact", T_BOOL),
    }


class DataPageHeader(ThriftStruct):
    FIELDS = {
        1: ("num_values", T_I32),
        2: ("encoding", T_I32),
        3: ("definition_level_encoding", T_I32),
        4: ("repetition_level_encoding", T_I32),
        5: ("statistics", Statistics),
    }


class IndexPageHeader(ThriftStruct):
    FIELDS = {}


class DictionaryPageHeader(ThriftStruct):
    FIELDS = {
        1: ("num_values", T_I32),
        2: ("encoding", T_I32),
        3: ("is_sorted", T_BOOL),
    }


class DataPageHeaderV2(ThriftStruct):
    FIELDS = {
        1: ("num_values", T_I32),
        2: ("num_nulls", T_I32),
        3: ("num_rows", T_I32),
        4: ("encoding", T_I32),
        5: ("definition_levels_byte_length", T_I32),
        6: ("repetition_levels_byte_length", T_I32),
        7: ("is_compressed", T_BOOL),
        8: ("statistics", Statistics),
    }


class PageHeader(ThriftStruct):
    FIELDS = {
        1: ("type", T_I32),
        2: ("uncompressed_page_size", T_I32),
        3: ("compressed_page_size", T_I32),
        4: ("crc", T_I32),
        5: ("data_page_header", DataPageHeader),
        6: ("index_page_header", IndexPageHeader),
        7: ("dictionary_page_header", DictionaryPageHeader),
        8: ("data_page_header_v2", DataPageHeaderV2),
    }


# ---------------------------------------------------------------------------
# Column chunks / row groups / file metadata
# ---------------------------------------------------------------------------

class KeyValue(ThriftStruct):
    FIELDS = {1: ("key", T_STRING), 2: ("value", T_STRING)}


class SortingColumn(ThriftStruct):
    FIELDS = {
        1: ("column_idx", T_I32),
        2: ("descending", T_BOOL),
        3: ("nulls_first", T_BOOL),
    }


class PageEncodingStats(ThriftStruct):
    FIELDS = {
        1: ("page_type", T_I32),
        2: ("encoding", T_I32),
        3: ("count", T_I32),
    }


class SizeStatistics(ThriftStruct):
    FIELDS = {
        1: ("unencoded_byte_array_data_bytes", T_I64),
        2: ("repetition_level_histogram", TList(T_I64)),
        3: ("definition_level_histogram", TList(T_I64)),
    }


class ColumnMetaData(ThriftStruct):
    FIELDS = {
        1: ("type", T_I32),
        2: ("encodings", TList(T_I32)),
        3: ("path_in_schema", TList(T_STRING)),
        4: ("codec", T_I32),
        5: ("num_values", T_I64),
        6: ("total_uncompressed_size", T_I64),
        7: ("total_compressed_size", T_I64),
        8: ("key_value_metadata", TList(KeyValue)),
        9: ("data_page_offset", T_I64),
        10: ("index_page_offset", T_I64),
        11: ("dictionary_page_offset", T_I64),
        12: ("statistics", Statistics),
        13: ("encoding_stats", TList(PageEncodingStats)),
        14: ("bloom_filter_offset", T_I64),
        15: ("bloom_filter_length", T_I32),
        16: ("size_statistics", SizeStatistics),
    }


class ColumnChunk(ThriftStruct):
    FIELDS = {
        1: ("file_path", T_STRING),
        2: ("file_offset", T_I64),
        3: ("meta_data", ColumnMetaData),
        4: ("offset_index_offset", T_I64),
        5: ("offset_index_length", T_I32),
        6: ("column_index_offset", T_I64),
        7: ("column_index_length", T_I32),
        9: ("encrypted_column_metadata", T_BINARY),
    }


class RowGroup(ThriftStruct):
    FIELDS = {
        1: ("columns", TList(ColumnChunk)),
        2: ("total_byte_size", T_I64),
        3: ("num_rows", T_I64),
        4: ("sorting_columns", TList(SortingColumn)),
        5: ("file_offset", T_I64),
        6: ("total_compressed_size", T_I64),
        7: ("ordinal", T_I16),
    }


class TypeDefinedOrder(ThriftStruct):
    FIELDS = {}


class ColumnOrder(ThriftStruct):
    """Union."""

    FIELDS = {1: ("TYPE_ORDER", TypeDefinedOrder)}


class FileMetaData(ThriftStruct):
    FIELDS = {
        1: ("version", T_I32),
        2: ("schema", TList(SchemaElement)),
        3: ("num_rows", T_I64),
        4: ("row_groups", TList(RowGroup)),
        5: ("key_value_metadata", TList(KeyValue)),
        6: ("created_by", T_STRING),
        7: ("column_orders", TList(ColumnOrder)),
    }


# Offset/column index structures (page-level indexes; written by modern
# writers, readable here for completeness of the metadata surface).

class PageLocation(ThriftStruct):
    FIELDS = {
        1: ("offset", T_I64),
        2: ("compressed_page_size", T_I32),
        3: ("first_row_index", T_I64),
    }


class OffsetIndex(ThriftStruct):
    FIELDS = {
        1: ("page_locations", TList(PageLocation)),
        2: ("unencoded_byte_array_data_bytes", TList(T_I64)),
    }


class ColumnIndex(ThriftStruct):
    FIELDS = {
        1: ("null_pages", TList(T_BOOL)),
        2: ("min_values", TList(T_BINARY)),
        3: ("max_values", TList(T_BINARY)),
        4: ("boundary_order", T_I32),
        5: ("null_counts", TList(T_I64)),
        6: ("repetition_level_histograms", TList(T_I64)),
        7: ("definition_level_histograms", TList(T_I64)),
    }
