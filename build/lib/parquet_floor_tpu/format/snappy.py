"""Snappy block format, implemented from scratch (no third-party codec).

The reference gets Snappy transitively via parquet-mr's JNI-wrapped
snappy-java (SURVEY.md §2.4 item 1; the shim seam is
``io/compress/CompressionCodec.java:6-11``).  Here the format itself is
implemented: a pure-Python reference (this module) and a C++ fast path
(``parquet_floor_tpu/native``) loaded via ctypes, selected automatically in
:mod:`parquet_floor_tpu.format.codecs`.

Block format (public Snappy format description):
  * stream := uncompressed-length varint, then elements
  * element tag low 2 bits: 0 literal / 1 copy-1B-offset / 2 copy-2B / 3 copy-4B
  * literal: upper 6 bits = len-1, or 60..63 → len-1 in next 1..4 LE bytes
  * copy1: len = ((tag>>2)&7)+4 (4..11), offset = ((tag>>5)<<8) | next byte
  * copy2: len = (tag>>2)+1 (1..64), offset = next 2 LE bytes
  * copy4: len = (tag>>2)+1, offset = next 4 LE bytes
  * copies may overlap (offset < len repeats the pattern)
"""

from __future__ import annotations

MAX_OFFSET_1B = 1 << 11  # 2048
_HASH_BITS = 14
_HASH_SIZE = 1 << _HASH_BITS


class SnappyError(ValueError):
    pass


def _read_varint(data, pos):
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise SnappyError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7
        if shift > 35:
            raise SnappyError("varint too long")


def decompress(data) -> bytes:
    """Decompress one Snappy block."""
    data = bytes(data)
    expected, pos = _read_varint(data, 0)
    out = bytearray(expected)
    opos = 0
    dlen = len(data)
    while pos < dlen:
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            ln = tag >> 2
            if ln >= 60:
                nbytes = ln - 59
                ln = int.from_bytes(data[pos : pos + nbytes], "little")
                pos += nbytes
            ln += 1
            if pos + ln > dlen or opos + ln > expected:
                raise SnappyError("literal overruns buffer")
            out[opos : opos + ln] = data[pos : pos + ln]
            pos += ln
            opos += ln
            continue
        nb = 1 if kind == 1 else 2 if kind == 2 else 4
        if pos + nb > dlen:
            raise SnappyError("truncated copy element")
        if kind == 1:
            ln = ((tag >> 2) & 0x7) + 4
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:
            ln = (tag >> 2) + 1
            offset = int.from_bytes(data[pos : pos + 2], "little")
            pos += 2
        else:
            ln = (tag >> 2) + 1
            offset = int.from_bytes(data[pos : pos + 4], "little")
            pos += 4
        if offset == 0 or offset > opos:
            raise SnappyError("copy offset out of range")
        if opos + ln > expected:
            raise SnappyError("copy overruns output")
        src = opos - offset
        if offset >= ln:
            out[opos : opos + ln] = out[src : src + ln]
            opos += ln
        else:
            # overlapping copy: repeat pattern byte-run by byte-run
            for _ in range(ln):
                out[opos] = out[src]
                opos += 1
                src += 1
    if opos != expected:
        raise SnappyError(f"decompressed size {opos} != header {expected}")
    return bytes(out)


def _emit_literal(out: bytearray, data, start: int, end: int) -> None:
    ln = end - start
    while ln > 0:
        chunk = min(ln, 0xFFFFFFFF)
        n = chunk - 1
        if n < 60:
            out.append(n << 2)
        elif n < (1 << 8):
            out.append(60 << 2)
            out.append(n)
        elif n < (1 << 16):
            out.append(61 << 2)
            out += n.to_bytes(2, "little")
        elif n < (1 << 24):
            out.append(62 << 2)
            out += n.to_bytes(3, "little")
        else:
            out.append(63 << 2)
            out += n.to_bytes(4, "little")
        out += data[start : start + chunk]
        start += chunk
        ln -= chunk


def _emit_copy(out: bytearray, offset: int, ln: int) -> None:
    # Long matches: emit 64-byte copy2/copy4 chunks, keep remainder >= 4.
    while ln >= 68:
        _emit_copy_upto64(out, offset, 64)
        ln -= 64
    if ln > 64:
        _emit_copy_upto64(out, offset, ln - 60)
        ln = 60
    _emit_copy_upto64(out, offset, ln)


def _emit_copy_upto64(out: bytearray, offset: int, ln: int) -> None:
    if 4 <= ln <= 11 and offset < MAX_OFFSET_1B:
        out.append(1 | ((ln - 4) << 2) | ((offset >> 8) << 5))
        out.append(offset & 0xFF)
    elif offset < (1 << 16):
        out.append(2 | ((ln - 1) << 2))
        out += offset.to_bytes(2, "little")
    else:
        out.append(3 | ((ln - 1) << 2))
        out += offset.to_bytes(4, "little")


def compress(data) -> bytes:
    """Greedy hash-table Snappy compressor (valid, reasonably effective)."""
    data = bytes(data)
    n = len(data)
    out = bytearray()
    _write_varint(out, n)
    if n < 16:
        if n:
            _emit_literal(out, data, 0, n)
        return bytes(out)

    table = [0] * _HASH_SIZE
    pos = 0
    lit_start = 0
    limit = n - 4
    while pos <= limit:
        h = ((int.from_bytes(data[pos : pos + 4], "little") * 0x1E35A7BD) >> (32 - _HASH_BITS)) & (
            _HASH_SIZE - 1
        )
        cand = table[h]
        table[h] = pos
        if (
            cand < pos
            and pos - cand < (1 << 16)
            and data[cand : cand + 4] == data[pos : pos + 4]
        ):
            # extend match
            mlen = 4
            maxm = n - pos
            while mlen < maxm and data[cand + mlen] == data[pos + mlen]:
                mlen += 1
            if lit_start < pos:
                _emit_literal(out, data, lit_start, pos)
            _emit_copy(out, pos - cand, mlen)
            pos += mlen
            lit_start = pos
        else:
            pos += 1
    if lit_start < n:
        _emit_literal(out, data, lit_start, n)
    return bytes(out)


def _write_varint(out: bytearray, v: int) -> None:
    while True:
        if v < 0x80:
            out.append(v)
            return
        out.append((v & 0x7F) | 0x80)
        v >>= 7
