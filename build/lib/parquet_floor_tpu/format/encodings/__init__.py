"""NumPy reference codecs for all Parquet page encodings."""
