"""BYTE_STREAM_SPLIT encoding (Parquet spec; Encoding id 9).

Transposes the bytes of fixed-width values into per-byte streams so that a
downstream block compressor sees long runs of similar bytes.  Pure shape
transform — NumPy transpose both ways, and on TPU a trivial relayout.
"""

from __future__ import annotations

import numpy as np


def encode_byte_stream_split(values: np.ndarray) -> bytes:
    v = np.ascontiguousarray(values)
    width = v.dtype.itemsize
    return v.view(np.uint8).reshape(-1, width).T.copy().tobytes()


def decode_byte_stream_split(data, num_values: int, dtype, pos: int = 0) -> np.ndarray:
    dtype = np.dtype(dtype)
    width = dtype.itemsize
    raw = np.frombuffer(data, dtype=np.uint8, count=num_values * width, offset=pos)
    return raw.reshape(width, num_values).T.copy().view(dtype).reshape(num_values)
