"""TPU decode engine: Pallas kernels + batched row-group reader."""
