"""Pallas TPU kernels for the columnar decode hot path."""
