#!/bin/sh
# Build the native runtime: g++ only, no external deps.
set -e
cd "$(dirname "$0")"
g++ -O3 -march=native -fPIC -shared -Wall -Wextra \
    -o libpftpu_native.so src/pftpu_native.cc src/pftpu_zstd.cc
echo "built $(pwd)/libpftpu_native.so"
