"""Native C++ runtime bindings (ctypes)."""
