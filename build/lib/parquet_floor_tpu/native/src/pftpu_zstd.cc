// pftpu_zstd: from-scratch Zstandard (RFC 8878) block decoder + store-mode
// encoder, plain C ABI for ctypes.
//
// Role in the framework: the reference reads any codec named in the footer by
// instantiating parquet-mr codec classes through its shim seam
// (ReflectionUtils.java:10-21, CompressionCodec.java:6-11), which JNI-wrap
// native libzstd [dep].  Here ZSTD is first-party: this file implements the
// decode side of RFC 8878 (FSE entropy, Huffman literals, sequence execution)
// and a spec-compliant raw-block ("store mode") encode side.  No external
// libraries.
//
// Scope notes:
//  * Dictionary frames (Dictionary_ID != 0) are rejected — Parquet pages are
//    self-contained frames; parquet-cpp/-mr never emit dictionary frames.
//  * Content checksums are skipped, not verified (XXH64 is not security
//    relevant for trusted-file decode; the Parquet page CRC covers integrity).
//  * Multiple concatenated frames and skippable frames are handled.

#include <cstddef>
#include <cstdint>
#include <cstring>

namespace {

// ---------------------------------------------------------------------------
// Bit readers
// ---------------------------------------------------------------------------

// Forward LSB-first bit reader (FSE table descriptions).
struct FwdBits {
  const uint8_t* p;
  size_t len;
  size_t bitpos = 0;
  bool ok = true;

  FwdBits(const uint8_t* p_, size_t len_) : p(p_), len(len_) {}

  uint32_t peek(int n) {
    uint64_t v = 0;
    size_t byte = bitpos >> 3;
    int shift = static_cast<int>(bitpos & 7);
    for (int i = 0; i < 8 && byte + i < len; i++) {
      v |= static_cast<uint64_t>(p[byte + i]) << (8 * i);
    }
    return static_cast<uint32_t>((v >> shift) & ((1u << n) - 1));
  }
  void consume(int n) {
    bitpos += n;
    if (bitpos > len * 8) ok = false;
  }
  size_t bytes_consumed() const { return (bitpos + 7) >> 3; }
};

// Backward bit reader (FSE/Huffman payload bitstreams).  Bits are numbered
// little-endian within the buffer; reading consumes from the top (just below
// the 1-bit end marker) downward.  Reads past the start return zero bits and
// flip `overflow` (the FSE weight stream relies on detecting this).
struct BackBits {
  const uint8_t* p;
  int64_t bitpos = -1;  // bits [0, bitpos) remain

  bool init(const uint8_t* p_, size_t len) {
    p = p_;
    if (len == 0 || p[len - 1] == 0) return false;
    int top = 7;
    while (!(p[len - 1] & (1 << top))) top--;
    bitpos = static_cast<int64_t>(len - 1) * 8 + top;  // marker excluded
    return true;
  }
  bool overflow() const { return bitpos < 0; }
  // Read n bits (n <= 32): result = bits [pos, pos+n) of the stream with
  // stream bit (pos+n-1) — the one nearest the marker — as the result MSB.
  uint32_t read(int n) {
    bitpos -= n;
    int64_t pos = bitpos;
    uint32_t v = 0;
    for (int k = 0; k < n; k++) {
      int64_t sb = pos + n - 1 - k;  // from MSB down
      uint32_t bit = 0;
      if (sb >= 0) bit = (p[sb >> 3] >> (sb & 7)) & 1;
      v = (v << 1) | bit;
    }
    return v;
  }
  uint32_t peek(int n) {
    int64_t save = bitpos;
    uint32_t v = read(n);
    bitpos = save;
    return v;
  }
  void skip(int n) { bitpos -= n; }
};

// ---------------------------------------------------------------------------
// FSE
// ---------------------------------------------------------------------------

constexpr int kMaxFseLog = 9;

struct FseEntry {
  uint8_t symbol;
  uint8_t nbits;
  uint16_t base;  // new-state baseline
};

struct FseTable {
  FseEntry e[1 << kMaxFseLog];
  int log = 0;
  bool rle = false;
  uint8_t rle_symbol = 0;
};

static int highbit(uint32_t v) {
  int r = 0;
  while (v > 1) {
    v >>= 1;
    r++;
  }
  return r;
}

// Build a decode table from normalized counts (count -1 == "less than one").
static bool fse_build(FseTable* t, const int16_t* norm, int n_sym, int log) {
  if (log > kMaxFseLog) return false;
  t->log = log;
  t->rle = false;
  const uint32_t size = 1u << log;
  uint32_t high = size - 1;
  uint16_t next[256];
  uint8_t sym_of[1 << kMaxFseLog];
  for (int s = 0; s < n_sym; s++) {
    if (norm[s] == -1) {
      sym_of[high--] = static_cast<uint8_t>(s);
      next[s] = 1;
    } else {
      next[s] = static_cast<uint16_t>(norm[s]);
    }
  }
  const uint32_t step = (size >> 1) + (size >> 3) + 3;
  const uint32_t mask = size - 1;
  uint32_t pos = 0;
  for (int s = 0; s < n_sym; s++) {
    for (int i = 0; i < norm[s]; i++) {
      sym_of[pos] = static_cast<uint8_t>(s);
      pos = (pos + step) & mask;
      while (pos > high) pos = (pos + step) & mask;
    }
  }
  if (pos != 0) return false;  // table not exactly filled
  for (uint32_t u = 0; u < size; u++) {
    uint8_t s = sym_of[u];
    uint16_t x = next[s]++;
    int nb = log - highbit(x);
    t->e[u].symbol = s;
    t->e[u].nbits = static_cast<uint8_t>(nb);
    t->e[u].base = static_cast<uint16_t>((x << nb) - size);
  }
  return true;
}

// Parse an FSE table description (forward bitstream).  Returns bytes
// consumed, or -1.  max_log/max_sym bound the field being read.
static ptrdiff_t fse_read_desc(const uint8_t* src, size_t len, FseTable* t,
                               int max_log, int max_sym) {
  FwdBits bits(src, len);
  int log = bits.peek(4) + 5;
  bits.consume(4);
  if (log > max_log) return -1;
  int16_t norm[256] = {0};
  int32_t remaining = (1 << log) + 1;
  int32_t threshold = 1 << log;
  int nbits = log + 1;
  int sym = 0;
  while (remaining > 1) {
    if (sym > max_sym || !bits.ok) return -1;
    int32_t maxv = (2 * threshold - 1) - remaining;
    uint32_t v = bits.peek(nbits);
    int32_t count;
    if (static_cast<int32_t>(v & (threshold - 1)) < maxv) {
      count = v & (threshold - 1);
      bits.consume(nbits - 1);
    } else {
      count = v & (2 * threshold - 1);
      if (count >= threshold) count -= maxv;
      bits.consume(nbits);
    }
    count--;  // -1 encodes "less than one"
    norm[sym++] = static_cast<int16_t>(count);
    remaining -= count < 0 ? -count : count;
    if (count == 0) {
      for (;;) {
        uint32_t rep = bits.peek(2);
        bits.consume(2);
        for (uint32_t i = 0; i < rep; i++) {
          if (sym > max_sym) return -1;
          norm[sym++] = 0;
        }
        if (rep != 3) break;
      }
    }
    while (remaining > 1 && remaining < threshold) {
      threshold >>= 1;
      nbits--;
    }
  }
  if (!bits.ok) return -1;
  if (!fse_build(t, norm, sym, log)) return -1;
  return static_cast<ptrdiff_t>(bits.bytes_consumed());
}

static void fse_rle_table(FseTable* t, uint8_t symbol) {
  t->rle = true;
  t->rle_symbol = symbol;
  t->log = 0;
  t->e[0].symbol = symbol;
  t->e[0].nbits = 0;
  t->e[0].base = 0;
}

// ---------------------------------------------------------------------------
// Huffman
// ---------------------------------------------------------------------------

constexpr int kMaxHufLog = 11;

struct HufTable {
  uint8_t symbol[1 << kMaxHufLog];
  uint8_t nbits[1 << kMaxHufLog];
  int log = 0;
  bool valid = false;
};

// Build the literals decode table from weights[0..n) plus the implicit last
// weight.
static bool huf_build(HufTable* t, const uint8_t* weights, int n) {
  if (n < 1 || n > 255) return false;
  uint64_t total = 0;
  for (int i = 0; i < n; i++) {
    if (weights[i] > kMaxHufLog) return false;
    if (weights[i]) total += 1ull << (weights[i] - 1);
  }
  if (total == 0) return false;
  // implicit last weight completes the next power of two
  int max_bits = highbit(static_cast<uint32_t>(total)) + 1;
  uint64_t target = 1ull << max_bits;
  uint64_t rest = target - total;
  if (rest == 0 || (rest & (rest - 1))) return false;  // must be a power of 2
  int last_w = highbit(static_cast<uint32_t>(rest)) + 1;
  if (max_bits > kMaxHufLog) return false;
  uint8_t w[256];
  memcpy(w, weights, n);
  w[n] = static_cast<uint8_t>(last_w);
  int n_sym = n + 1;
  t->log = max_bits;
  uint32_t pos = 0;
  for (int wt = 1; wt <= max_bits; wt++) {
    for (int s = 0; s < n_sym; s++) {
      if (w[s] != wt) continue;
      uint32_t span = 1u << (wt - 1);
      int nb = max_bits + 1 - wt;
      for (uint32_t i = 0; i < span; i++) {
        t->symbol[pos + i] = static_cast<uint8_t>(s);
        t->nbits[pos + i] = static_cast<uint8_t>(nb);
      }
      pos += span;
    }
  }
  if (pos != (1u << max_bits)) return false;
  t->valid = true;
  return true;
}

// Read a Huffman tree description.  Returns bytes consumed or -1.
static ptrdiff_t huf_read_desc(const uint8_t* src, size_t len, HufTable* t) {
  if (len < 1) return -1;
  int hdr = src[0];
  uint8_t weights[255];
  int n;
  size_t used;
  if (hdr >= 128) {  // direct: 4-bit weights
    n = hdr - 127;
    size_t nbytes = (static_cast<size_t>(n) + 1) / 2;
    if (1 + nbytes > len) return -1;
    for (int i = 0; i < n; i++) {
      uint8_t b = src[1 + i / 2];
      weights[i] = (i % 2 == 0) ? (b >> 4) : (b & 0xF);
    }
    used = 1 + nbytes;
  } else {  // FSE-compressed weights, two interleaved states
    size_t csize = hdr;
    if (1 + csize > len) return -1;
    FseTable ft;
    ptrdiff_t hs = fse_read_desc(src + 1, csize, &ft, 6, 255);
    if (hs < 0) return -1;
    BackBits bb;
    if (!bb.init(src + 1 + hs, csize - hs)) return -1;
    uint32_t s1 = bb.read(ft.log);
    uint32_t s2 = bb.read(ft.log);
    if (bb.overflow()) return -1;
    n = 0;
    // mirror of zstd's FSE_decompress tail loop: alternate states until the
    // bitstream over-reads, then flush the other state once
    for (;;) {
      if (n >= 254) return -1;
      weights[n++] = ft.e[s1].symbol;
      s1 = ft.e[s1].base + bb.read(ft.e[s1].nbits);
      if (bb.overflow()) {
        weights[n++] = ft.e[s2].symbol;
        break;
      }
      if (n >= 254) return -1;
      weights[n++] = ft.e[s2].symbol;
      s2 = ft.e[s2].base + bb.read(ft.e[s2].nbits);
      if (bb.overflow()) {
        weights[n++] = ft.e[s1].symbol;
        break;
      }
    }
    used = 1 + csize;
  }
  if (!huf_build(t, weights, n)) return -1;
  return static_cast<ptrdiff_t>(used);
}

// Decode one Huffman bitstream into out[0..count).
static bool huf_stream(const HufTable& t, const uint8_t* src, size_t len,
                       uint8_t* out, size_t count) {
  BackBits bb;
  if (!bb.init(src, len)) return false;
  for (size_t i = 0; i < count; i++) {
    uint32_t idx = bb.peek(t.log);  // zero-padded near the end by design
    out[i] = t.symbol[idx];
    bb.skip(t.nbits[idx]);
    if (bb.bitpos < -7) return false;  // clearly past the end: corrupt
  }
  return true;
}

// ---------------------------------------------------------------------------
// Sequences: baselines + predefined distributions (RFC 8878 §3.1.1.3.2.2)
// ---------------------------------------------------------------------------

static const uint32_t kLLBase[36] = {
    0,  1,  2,   3,   4,   5,    6,    7,    8,    9,     10,    11,
    12, 13, 14,  15,  16,  18,   20,   22,   24,   28,    32,    40,
    48, 64, 128, 256, 512, 1024, 2048, 4096, 8192, 16384, 32768, 65536};
static const uint8_t kLLBits[36] = {0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
                                    0, 0, 0, 0, 1, 1, 1, 1, 2, 2, 3, 3,
                                    4, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16};
static const uint32_t kMLBase[53] = {
    3,  4,  5,  6,  7,  8,  9,  10, 11, 12, 13, 14, 15, 16,  17,  18,  19, 20,
    21, 22, 23, 24, 25, 26, 27, 28, 29, 30, 31, 32, 33, 34,  35,  37,  39, 41,
    43, 47, 51, 59, 67, 83, 99, 131, 259, 515, 1027, 2051, 4099, 8195, 16387,
    32771, 65539};
static const uint8_t kMLBits[53] = {0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
                                    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
                                    0, 0, 0, 0, 1, 1, 1, 1, 2, 2, 3, 3, 4, 4,
                                    5, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16};

static const int16_t kLLNorm[36] = {4, 3, 2, 2, 2, 2, 2, 2, 2, 2, 2, 2,
                                    2, 1, 1, 1, 2, 2, 2, 2, 2, 2, 2, 2,
                                    2, 3, 2, 1, 1, 1, 1, 1, -1, -1, -1, -1};
static const int16_t kOFNorm[29] = {1, 1, 1, 1, 1, 1, 2, 2, 2, 1,
                                    1, 1, 1, 1, 1, 1, 1, 1, 1, 1,
                                    1, 1, 1, 1, -1, -1, -1, -1, -1};
static const int16_t kMLNorm[53] = {1, 4, 3, 2, 2, 2, 2, 2, 2, 1, 1, 1, 1, 1,
                                    1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1,
                                    1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1,
                                    1, 1, 1, 1, -1, -1, -1, -1, -1, -1, -1};

// ---------------------------------------------------------------------------
// Frame decoding state
// ---------------------------------------------------------------------------

struct ZstdCtx {
  HufTable huf;             // persists across blocks within a frame
  FseTable ll, of, ml;      // ditto
  bool have_ll = false, have_of = false, have_ml = false;
  uint32_t rep[3] = {1, 4, 8};
  uint8_t literals[1 << 17];  // one block's literals (<= 128 KiB)
};

// Decode the literals section.  Sets *lit_len, advances *src.
static bool decode_literals(ZstdCtx* ctx, const uint8_t** src,
                            const uint8_t* end, size_t* lit_len) {
  const uint8_t* p = *src;
  if (p >= end) return false;
  int type = p[0] & 3;
  int sf = (p[0] >> 2) & 3;
  size_t regen, csize = 0, lh;
  bool single_stream = false;
  if (type <= 1) {  // Raw / RLE
    switch (sf) {
      case 0:
      case 2:
        lh = 1;
        regen = p[0] >> 3;
        break;
      case 1:
        if (p + 2 > end) return false;
        lh = 2;
        regen = (p[0] >> 4) | (static_cast<size_t>(p[1]) << 4);
        break;
      default:
        if (p + 3 > end) return false;
        lh = 3;
        regen = (p[0] >> 4) | (static_cast<size_t>(p[1]) << 4) |
                (static_cast<size_t>(p[2]) << 12);
        break;
    }
    if (regen > sizeof(ctx->literals)) return false;
    if (type == 0) {  // Raw
      if (p + lh + regen > end) return false;
      memcpy(ctx->literals, p + lh, regen);
      *src = p + lh + regen;
    } else {  // RLE
      if (p + lh + 1 > end) return false;
      memset(ctx->literals, p[lh], regen);
      *src = p + lh + 1;
    }
    *lit_len = regen;
    return true;
  }
  // Compressed (2) / Treeless (3)
  switch (sf) {
    case 0:
      single_stream = true;
      [[fallthrough]];
    case 1: {
      if (p + 3 > end) return false;
      uint32_t v = p[0] | (p[1] << 8) | (p[2] << 16);
      lh = 3;
      regen = (v >> 4) & 0x3FF;
      csize = v >> 14;
      break;
    }
    case 2: {
      if (p + 4 > end) return false;
      uint32_t v = p[0] | (p[1] << 8) | (p[2] << 16) |
                   (static_cast<uint32_t>(p[3]) << 24);
      lh = 4;
      regen = (v >> 4) & 0x3FFF;
      csize = v >> 18;
      break;
    }
    default: {
      if (p + 5 > end) return false;
      uint64_t v = static_cast<uint64_t>(p[0]) | (static_cast<uint64_t>(p[1]) << 8) |
                   (static_cast<uint64_t>(p[2]) << 16) |
                   (static_cast<uint64_t>(p[3]) << 24) |
                   (static_cast<uint64_t>(p[4]) << 32);
      lh = 5;
      regen = (v >> 4) & 0x3FFFF;
      csize = v >> 22;
      break;
    }
  }
  if (regen > sizeof(ctx->literals)) return false;
  if (p + lh + csize > end) return false;
  const uint8_t* hp = p + lh;
  size_t hlen = csize;
  if (type == 2) {  // new Huffman table
    ptrdiff_t used = huf_read_desc(hp, hlen, &ctx->huf);
    if (used < 0) return false;
    hp += used;
    hlen -= used;
  } else if (!ctx->huf.valid) {
    return false;  // treeless with no previous table
  }
  if (single_stream) {
    if (!huf_stream(ctx->huf, hp, hlen, ctx->literals, regen)) return false;
  } else {
    if (hlen < 6) return false;
    size_t s1 = hp[0] | (hp[1] << 8);
    size_t s2 = hp[2] | (hp[3] << 8);
    size_t s3 = hp[4] | (hp[5] << 8);
    if (6 + s1 + s2 + s3 > hlen) return false;
    size_t s4 = hlen - 6 - s1 - s2 - s3;
    size_t per = (regen + 3) / 4;
    if (per * 3 > regen) return false;
    const uint8_t* sp = hp + 6;
    if (!huf_stream(ctx->huf, sp, s1, ctx->literals, per)) return false;
    if (!huf_stream(ctx->huf, sp + s1, s2, ctx->literals + per, per)) return false;
    if (!huf_stream(ctx->huf, sp + s1 + s2, s3, ctx->literals + 2 * per, per))
      return false;
    if (!huf_stream(ctx->huf, sp + s1 + s2 + s3, s4, ctx->literals + 3 * per,
                    regen - 3 * per))
      return false;
  }
  *src = p + lh + csize;
  *lit_len = regen;
  return true;
}

// Read one sequence-field table per its 2-bit mode.
static bool seq_table(int mode, FseTable* t, bool* have,
                      const int16_t* def_norm, int def_nsym, int def_log,
                      int max_log, int max_sym, const uint8_t** src,
                      const uint8_t* end) {
  switch (mode) {
    case 0:  // predefined
      if (!fse_build(t, def_norm, def_nsym, def_log)) return false;
      *have = true;
      return true;
    case 1:  // RLE: single byte symbol
      if (*src >= end) return false;
      if (**src > max_sym) return false;
      fse_rle_table(t, **src);
      (*src)++;
      *have = true;
      return true;
    case 2: {  // FSE description
      ptrdiff_t used = fse_read_desc(*src, end - *src, t, max_log, max_sym);
      if (used < 0) return false;
      *src += used;
      *have = true;
      return true;
    }
    default:  // repeat
      return *have;
  }
}

// Decode + execute one compressed block.  Returns bytes written to dst, -1
// on corruption, -2 on dst capacity exhaustion.  frame_base marks where the
// current frame's output began: match offsets may not reach past it.
static ptrdiff_t decode_block(ZstdCtx* ctx, const uint8_t* src, size_t len,
                              uint8_t* dst, size_t dst_cap, size_t dst_done,
                              size_t frame_base) {
  const uint8_t* p = src;
  const uint8_t* end = src + len;
  size_t lit_len;
  if (!decode_literals(ctx, &p, end, &lit_len)) return -1;
  if (p >= end) return -1;
  // sequences count
  size_t nseq;
  if (p[0] < 128) {
    nseq = p[0];
    p += 1;
  } else if (p[0] < 255) {
    if (p + 2 > end) return -1;
    nseq = (static_cast<size_t>(p[0] - 128) << 8) + p[1];
    p += 2;
  } else {
    if (p + 3 > end) return -1;
    nseq = p[1] + (static_cast<size_t>(p[2]) << 8) + 0x7F00;
    p += 3;
  }
  uint8_t* out = dst + dst_done;
  size_t cap = dst_cap - dst_done;
  if (nseq == 0) {
    if (lit_len > cap) return -2;  // -2: dst capacity exhausted
    memcpy(out, ctx->literals, lit_len);
    return static_cast<ptrdiff_t>(lit_len);
  }
  if (p >= end) return -1;
  int modes = *p++;
  if (!seq_table((modes >> 6) & 3, &ctx->ll, &ctx->have_ll, kLLNorm, 36, 6,
                 9, 35, &p, end))
    return -1;
  if (!seq_table((modes >> 4) & 3, &ctx->of, &ctx->have_of, kOFNorm, 29, 5,
                 8, 31, &p, end))
    return -1;
  if (!seq_table((modes >> 2) & 3, &ctx->ml, &ctx->have_ml, kMLNorm, 53, 6,
                 9, 52, &p, end))
    return -1;
  BackBits bb;
  if (!bb.init(p, end - p)) return -1;
  uint32_t ll_s = bb.read(ctx->ll.log);
  uint32_t of_s = bb.read(ctx->of.log);
  uint32_t ml_s = bb.read(ctx->ml.log);
  if (bb.overflow()) return -1;
  size_t out_pos = 0;
  size_t lit_pos = 0;
  for (size_t i = 0; i < nseq; i++) {
    int of_code = ctx->of.e[of_s].symbol;
    int ml_code = ctx->ml.e[ml_s].symbol;
    int ll_code = ctx->ll.e[ll_s].symbol;
    if (of_code > 31 || ml_code > 52 || ll_code > 35) return -1;
    // value bits are read OF, ML, LL
    uint64_t of_val =
        (1ull << of_code) + ((of_code > 0) ? bb.read(of_code) : 0u);
    uint32_t match = kMLBase[ml_code] + (kMLBits[ml_code] ? bb.read(kMLBits[ml_code]) : 0);
    uint32_t lit = kLLBase[ll_code] + (kLLBits[ll_code] ? bb.read(kLLBits[ll_code]) : 0);
    if (bb.overflow()) return -1;
    // resolve offset against the repeat history
    uint32_t offset;
    if (of_val <= 3) {
      uint32_t idx = static_cast<uint32_t>(of_val) - 1 + (lit == 0 ? 1 : 0);
      if (idx == 0) {
        offset = ctx->rep[0];
      } else if (idx == 1) {
        offset = ctx->rep[1];
        ctx->rep[1] = ctx->rep[0];
        ctx->rep[0] = offset;
      } else if (idx == 2) {
        offset = ctx->rep[2];
        ctx->rep[2] = ctx->rep[1];
        ctx->rep[1] = ctx->rep[0];
        ctx->rep[0] = offset;
      } else {  // idx == 3: rep[0] - 1
        if (ctx->rep[0] <= 1) return -1;
        offset = ctx->rep[0] - 1;
        ctx->rep[2] = ctx->rep[1];
        ctx->rep[1] = ctx->rep[0];
        ctx->rep[0] = offset;
      }
    } else {
      offset = static_cast<uint32_t>(of_val - 3);
      ctx->rep[2] = ctx->rep[1];
      ctx->rep[1] = ctx->rep[0];
      ctx->rep[0] = offset;
    }
    // copy literals
    if (lit_pos + lit > lit_len) return -1;
    if (out_pos + lit > cap) return -2;
    memcpy(out + out_pos, ctx->literals + lit_pos, lit);
    lit_pos += lit;
    out_pos += lit;
    // copy match (may overlap)
    if (offset == 0 || offset > (dst_done - frame_base) + out_pos) return -1;
    if (out_pos + match > cap) return -2;
    const uint8_t* from = out + out_pos - offset;
    for (uint32_t k = 0; k < match; k++) out[out_pos + k] = from[k];
    out_pos += match;
    // state updates (order LL, ML, OF), not after the last sequence
    if (i + 1 < nseq) {
      ll_s = ctx->ll.e[ll_s].base + bb.read(ctx->ll.e[ll_s].nbits);
      ml_s = ctx->ml.e[ml_s].base + bb.read(ctx->ml.e[ml_s].nbits);
      of_s = ctx->of.e[of_s].base + bb.read(ctx->of.e[of_s].nbits);
      if (bb.overflow()) return -1;
    }
  }
  // trailing literals
  size_t rest = lit_len - lit_pos;
  if (out_pos + rest > cap) return -2;
  memcpy(out + out_pos, ctx->literals + lit_pos, rest);
  out_pos += rest;
  return static_cast<ptrdiff_t>(out_pos);
}

}  // namespace

extern "C" {

// Decompress a sequence of zstd frames.  Returns bytes written or -1.
ptrdiff_t pftpu_zstd_decompress(const uint8_t* src, size_t src_len,
                                uint8_t* dst, size_t dst_cap) {
  const uint8_t* p = src;
  const uint8_t* end = src + src_len;
  size_t done = 0;
  while (p < end) {
    if (p + 4 > end) return -1;
    uint32_t magic = p[0] | (p[1] << 8) | (p[2] << 16) |
                     (static_cast<uint32_t>(p[3]) << 24);
    p += 4;
    if ((magic & 0xFFFFFFF0u) == 0x184D2A50u) {  // skippable frame
      if (p + 4 > end) return -1;
      uint32_t sz = p[0] | (p[1] << 8) | (p[2] << 16) |
                    (static_cast<uint32_t>(p[3]) << 24);
      p += 4;
      if (p + sz > end) return -1;
      p += sz;
      continue;
    }
    if (magic != 0xFD2FB528u) return -1;
    if (p >= end) return -1;
    uint8_t fhd = *p++;
    int dict_flag = fhd & 3;
    bool checksum = fhd & 4;
    if (fhd & 8) return -1;  // reserved bit
    bool single_seg = fhd & 32;
    int fcs_flag = fhd >> 6;
    if (!single_seg) {
      if (p >= end) return -1;
      p++;  // window descriptor: decode into caller's buffer, value unused
    }
    static const int kDictLen[4] = {0, 1, 2, 4};
    uint32_t dict_id = 0;
    if (p + kDictLen[dict_flag] > end) return -1;
    for (int i = 0; i < kDictLen[dict_flag]; i++)
      dict_id |= static_cast<uint32_t>(p[i]) << (8 * i);
    p += kDictLen[dict_flag];
    if (dict_id != 0) return -1;  // dictionary frames unsupported
    int fcs_len = 0;
    if (fcs_flag == 0) fcs_len = single_seg ? 1 : 0;
    else if (fcs_flag == 1) fcs_len = 2;
    else if (fcs_flag == 2) fcs_len = 4;
    else fcs_len = 8;
    if (p + fcs_len > end) return -1;
    p += fcs_len;  // dst_cap is authoritative (parquet header gives it)
    // blocks
    ZstdCtx ctx;  // per-frame entropy state
    const size_t frame_base = done;
    for (;;) {
      if (p + 3 > end) return -1;
      uint32_t bh = p[0] | (p[1] << 8) | (p[2] << 16);
      p += 3;
      bool last = bh & 1;
      int btype = (bh >> 1) & 3;
      size_t bsize = bh >> 3;
      switch (btype) {
        case 0:  // raw
          if (p + bsize > end) return -1;
          if (done + bsize > dst_cap) return -2;
          memcpy(dst + done, p, bsize);
          p += bsize;
          done += bsize;
          break;
        case 1:  // RLE: bsize is the regenerated size, one payload byte
          if (p >= end) return -1;
          if (done + bsize > dst_cap) return -2;
          memset(dst + done, *p, bsize);
          p += 1;
          done += bsize;
          break;
        case 2: {  // compressed
          if (p + bsize > end) return -1;
          ptrdiff_t n =
              decode_block(&ctx, p, bsize, dst, dst_cap, done, frame_base);
          if (n < 0) return n;
          p += bsize;
          done += static_cast<size_t>(n);
          break;
        }
        default:
          return -1;  // reserved
      }
      if (last) break;
    }
    if (checksum) {
      if (p + 4 > end) return -1;
      p += 4;  // XXH64 low 32 bits: skipped (see header comment)
    }
  }
  return static_cast<ptrdiff_t>(done);
}

// Store-mode compressor: emits one frame of raw blocks.  Valid zstd that any
// decoder accepts; used for the (non-hot) write path.
size_t pftpu_zstd_max_compressed_size(size_t n) {
  size_t blocks = n / (128 * 1024) + 1;
  return n + blocks * 3 + 18;
}

ptrdiff_t pftpu_zstd_compress_store(const uint8_t* src, size_t src_len,
                                    uint8_t* dst, size_t dst_cap) {
  uint8_t* q = dst;
  uint8_t* qend = dst + dst_cap;
  auto put = [&](uint8_t b) -> bool {
    if (q >= qend) return false;
    *q++ = b;
    return true;
  };
  // magic
  const uint8_t magic[4] = {0x28, 0xB5, 0x2F, 0xFD};
  for (uint8_t b : magic)
    if (!put(b)) return -1;
  // frame header: single-segment, FCS sized to content
  int fcs_flag;
  int fcs_len;
  if (src_len <= 255) {
    fcs_flag = 0;
    fcs_len = 1;
  } else if (src_len <= 65535 + 256) {
    fcs_flag = 1;
    fcs_len = 2;
  } else if (src_len <= 0xFFFFFFFFull) {
    fcs_flag = 2;
    fcs_len = 4;
  } else {
    fcs_flag = 3;
    fcs_len = 8;
  }
  if (!put(static_cast<uint8_t>((fcs_flag << 6) | 32))) return -1;
  uint64_t fcs = (fcs_flag == 1) ? src_len - 256 : src_len;
  for (int i = 0; i < fcs_len; i++)
    if (!put(static_cast<uint8_t>(fcs >> (8 * i)))) return -1;
  // raw blocks
  size_t pos = 0;
  const size_t kBlock = 128 * 1024 - 1;
  do {
    size_t n = src_len - pos < kBlock ? src_len - pos : kBlock;
    bool last = pos + n == src_len;
    uint32_t bh = (static_cast<uint32_t>(n) << 3) | (last ? 1 : 0);
    if (!put(bh & 0xFF) || !put((bh >> 8) & 0xFF) || !put((bh >> 16) & 0xFF))
      return -1;
    if (q + n > qend) return -1;
    memcpy(q, src + pos, n);
    q += n;
    pos += n;
  } while (pos < src_len);
  return q - dst;
}

}  // extern "C"
