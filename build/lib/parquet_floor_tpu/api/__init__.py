"""L4: declarative API with reference parity (SURVEY.md §7 `api/`)."""
