"""Device-mesh sharding: row-group/column parallel decode via jax.sharding."""
