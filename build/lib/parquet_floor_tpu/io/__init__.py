"""L1: host filesystem sources/sinks."""
