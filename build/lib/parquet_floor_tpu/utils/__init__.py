"""Shared utilities."""
