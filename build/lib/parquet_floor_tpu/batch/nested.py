"""Dremel record assembly: (values, def_levels, rep_levels) → nested columns.

The reference *facade* refuses repeated columns outright
(``ParquetReader.java:200-202`` throws "Unexpected repetition") while the
parquet-mr engine underneath can decode them; this module supplies the
engine-level capability (SURVEY.md §7 hard part 5, BASELINE config #5):
assembling Parquet's flattened Dremel encoding back into nested lists.

Two consumers:

* ``assemble_nested`` — vectorized NumPy assembly into per-depth offset +
  validity arrays (the Arrow-style columnar form; what batch/TPU callers
  want).  All O(n) work is array ops: ``flatnonzero`` for slot starts,
  ``add.reduceat`` for element counts.
* ``NestedColumn.to_pylist`` — exact recursive rendering to Python lists
  (``None`` for nulls), the oracle form interop tests compare against
  pyarrow's ``to_pylist``.

Level semantics implemented here (Dremel, per the format spec):

* each **optional** node on a leaf's path adds 1 definition level;
* each **repeated** node adds 1 definition level *and* 1 repetition level;
* a value slot's definition level says how deep its path is defined:
  ``def == d_node - 1`` at an optional node means *null here*, at a
  repeated node means *empty list here*;
* a position's repetition level says at which repeated depth the record
  "restarts": ``rep == r`` begins a new element of the depth-``r`` list.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Union

import numpy as np

from ..format.encodings.plain import ByteArrayColumn
from ..format.schema import ColumnDescriptor, MessageType, SchemaNode


@dataclass(frozen=True)
class LevelNode:
    """One definition-level-bearing node on a leaf's path."""

    kind: str        # "optional" | "repeated"
    def_level: int   # cumulative max_def INCLUDING this node
    rep_level: int   # cumulative max_rep INCLUDING this node
    name: str
    is_leaf: bool


def level_chain(schema: MessageType, path: Sequence[str]) -> List[LevelNode]:
    """Walk the schema root→leaf along ``path`` collecting the nodes that
    carry definition levels (optional/repeated); required nodes carry none.
    """
    chain: List[LevelNode] = []
    node: SchemaNode = schema
    d = r = 0
    for depth, part in enumerate(path):
        nxt = None
        for f in node.fields:
            if f.name == part:
                nxt = f
                break
        if nxt is None:
            raise KeyError(f"path {'.'.join(path)}: no field {part!r}")
        node = nxt
        is_leaf = depth == len(path) - 1
        if node.is_optional:
            d += 1
            chain.append(LevelNode("optional", d, r, part, is_leaf))
        elif node.is_repeated:
            d += 1
            r += 1
            chain.append(LevelNode("repeated", d, r, part, is_leaf))
        if is_leaf and not node.is_primitive:
            raise ValueError(f"path {'.'.join(path)} is not a leaf")
    return chain


@dataclass
class DepthInfo:
    """Offsets+validity for one repeated depth (Arrow ListArray layout).

    ``offsets[i]:offsets[i+1]`` indexes the next depth's slots (or the leaf
    elements at the deepest depth).  ``valid[i]`` is False when the list
    slot is null (an optional node at-or-above this repeated node, below
    the previous one, was undefined); an empty-but-present list has
    ``valid[i] == True`` and zero length.
    """

    offsets: np.ndarray   # int64[n_slots + 1]
    valid: np.ndarray     # bool[n_slots]


@dataclass
class NestedColumn:
    """One leaf column assembled into nested (list…) form."""

    descriptor: ColumnDescriptor
    chain: List[LevelNode]
    depths: List[DepthInfo]            # one per repeated depth, outermost first
    leaf_present: np.ndarray           # bool[n_leaf_slots]: value not null
    values: Union[np.ndarray, ByteArrayColumn]  # dense non-null leaf values
    def_levels: np.ndarray
    rep_levels: np.ndarray

    @property
    def num_rows(self) -> int:
        return len(self.depths[0].offsets) - 1 if self.depths else len(self.leaf_present)

    def to_pylist(self) -> list:
        """Exact nested-Python rendering (the pyarrow-comparable oracle)."""
        return _to_pylist(
            self.chain, self.def_levels, self.rep_levels, self.values,
            self.descriptor.max_definition_level,
        )


def assemble_nested(schema: MessageType, batch) -> NestedColumn:
    """Assemble a decoded ``ColumnBatch`` (values + def/rep levels) into a
    ``NestedColumn``.  ``batch.rep_levels`` must be present (repeated leaf).
    """
    desc: ColumnDescriptor = batch.descriptor
    chain = level_chain(schema, desc.path)
    defs = np.asarray(batch.def_levels, dtype=np.int32)
    reps = np.asarray(batch.rep_levels, dtype=np.int32)
    max_def = desc.max_definition_level
    n = len(defs)

    rep_nodes = [c for c in chain if c.kind == "repeated"]
    depths: List[DepthInfo] = []
    prev_d = 0  # def threshold at which a slot for the current depth exists
    for node in rep_nodes:
        r, d = node.rep_level, node.def_level
        # slot starts: new instance of the parent context whose subtree is
        # defined at least to the previous repeated node
        start_mask = (reps < r) & (defs >= prev_d)
        starts = np.flatnonzero(start_mask)
        valid = defs[starts] >= d - 1  # below d-1 → an optional above is null
        # element count per slot: the start position itself contributes one
        # element when the list is non-empty, plus every rep==r continuation
        elem_start = (reps == r) | (start_mask & (defs >= d))
        if n:
            csum = np.concatenate(
                [[0], np.cumsum(elem_start.astype(np.int64))]
            )
            counts = csum[np.append(starts[1:], n)] - csum[starts]
        else:
            counts = np.zeros(0, dtype=np.int64)
        offsets = np.zeros(len(starts) + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        depths.append(DepthInfo(offsets=offsets, valid=valid))
        prev_d = d

    if rep_nodes:
        deepest = rep_nodes[-1]
        elem_mask = (reps == deepest.rep_level) | (
            (reps < deepest.rep_level) & (defs >= deepest.def_level)
        )
        leaf_present = defs[elem_mask] == max_def
    else:
        leaf_present = defs == max_def

    return NestedColumn(
        descriptor=desc,
        chain=chain,
        depths=depths,
        leaf_present=leaf_present,
        values=batch.values,
        def_levels=defs,
        rep_levels=reps,
    )


def _to_pylist(chain, defs, reps, values, max_def) -> list:
    """Recursive reference rendering; exact but not vectorized."""
    n = len(defs)
    # map level position → dense value index
    present = defs == max_def
    vidx = np.cumsum(present) - 1

    def value_at(pos: int):
        v = values[int(vidx[pos])]
        if isinstance(v, np.generic):
            v = v.item()
        return v

    def build(ci: int, lo: int, hi: int):
        if ci == len(chain):
            return value_at(lo)
        node = chain[ci]
        if node.kind == "optional":
            if defs[lo] < node.def_level:
                return None
            return build(ci + 1, lo, hi)
        # repeated
        if defs[lo] < node.def_level:
            return []
        r = node.rep_level
        starts = [lo] + [p for p in range(lo + 1, hi) if reps[p] == r]
        ends = starts[1:] + [hi]
        out = []
        for s, e in zip(starts, ends):
            # deeper continuations (rep > r) stay inside [s, e)
            out.append(build(ci + 1, s, e))
        return out

    rows = []
    row_starts = [p for p in range(n) if reps[p] == 0]
    row_ends = row_starts[1:] + [n]
    for s, e in zip(row_starts, row_ends):
        rows.append(build(0, s, e))
    return rows


# ---------------------------------------------------------------------------
# Write-side shredding: nested Python values → (values, def, rep)
# ---------------------------------------------------------------------------

def shred_nested(schema: MessageType, desc: ColumnDescriptor, rows: Sequence):
    """Shred one leaf column's nested Python rows into Dremel form.

    ``rows`` is one entry per record, shaped like the leaf's nesting:
    scalars (or None) for flat leaves, lists (possibly empty/None) at each
    repeated node.  Returns (leaf_values_list, def_levels, rep_levels).
    """
    chain = level_chain(schema, desc.path)
    defs: List[int] = []
    reps: List[int] = []
    out_vals: List = []

    def emit(d: int, r: int, val=None, have=False):
        defs.append(d)
        reps.append(r)
        if have:
            out_vals.append(val)

    def walk(ci: int, val, cur_def: int, rep_in: int):
        if ci == len(chain):
            if val is None:
                raise ValueError(
                    f"required leaf {'.'.join(desc.path)} got None"
                )
            emit(cur_def, rep_in, val, True)
            return
        node = chain[ci]
        if node.kind == "optional":
            if val is None:
                emit(node.def_level - 1, rep_in)
                return
            if ci == len(chain) - 1:  # optional leaf
                emit(node.def_level, rep_in, val, True)
                return
            walk(ci + 1, val, node.def_level, rep_in)
            return
        # repeated node
        if val is None or (hasattr(val, "__len__") and len(val) == 0):
            # null handled by an optional ancestor; here None ≈ empty list
            emit(node.def_level - 1, rep_in)
            return
        if not isinstance(val, (list, tuple, np.ndarray)):
            raise TypeError(
                f"repeated node {node.name!r} in {'.'.join(desc.path)} "
                f"expects a list, got {type(val).__name__}"
            )
        r_next = rep_in
        for item in val:
            if ci == len(chain) - 1:  # repeated leaf primitive
                if item is None:
                    raise ValueError("repeated leaf element cannot be None")
                emit(node.def_level, r_next, item, True)
            else:
                walk(ci + 1, item, node.def_level, r_next)
            r_next = node.rep_level
        return

    for row in rows:
        walk(0, row, 0, 0)

    return (
        out_vals,
        np.asarray(defs, dtype=np.uint32),
        np.asarray(reps, dtype=np.uint32),
    )
