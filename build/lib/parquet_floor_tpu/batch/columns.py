"""Columnar batch containers — the L3 materialization layer (SURVEY.md §1:
"columnar batch materialization (arrays, not per-row events)").

Where the reference surfaces one cell at a time through ``ColumnReader``
getters (``ParquetReader.java:141-168``), this framework decodes whole row
groups into arrays and serves both:
  * per-row cursors for the Hydrator-parity API, and
  * zero-copy columnar access for batch/TPU consumers (the native win).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Union

import numpy as np

from ..format.encodings.plain import ByteArrayColumn
from ..format.schema import ColumnDescriptor


@dataclass
class ColumnBatch:
    """All values of one column across a row-group's pages.

    ``values`` holds non-null leaf values only (length = count of
    def_levels == max_def, or num_values for required columns).
    """

    descriptor: ColumnDescriptor
    num_values: int  # total level count (rows for flat columns)
    values: Union[np.ndarray, ByteArrayColumn]
    def_levels: Optional[np.ndarray] = None
    rep_levels: Optional[np.ndarray] = None

    def __post_init__(self):
        self._value_index = None

    @property
    def is_flat(self) -> bool:
        return self.descriptor.max_repetition_level == 0

    @property
    def null_mask(self) -> Optional[np.ndarray]:
        """True where the slot is null; None when column is required."""
        if self.def_levels is None:
            return None
        return self.def_levels != self.descriptor.max_definition_level

    def _ensure_value_index(self):
        if self._value_index is None and self.def_levels is not None:
            present = self.def_levels == self.descriptor.max_definition_level
            self._value_index = np.cumsum(present) - 1
        return self._value_index

    def cell(self, i: int):
        """Row-level access for flat columns; None when null.

        Null semantics parity: a cell is null iff its definition level is
        below the max (reference ``ParquetReader.java:146,165-167``).
        """
        if not self.is_flat:
            raise ValueError("cell() requires a flat (non-repeated) column")
        if self.def_levels is not None:
            if self.def_levels[i] != self.descriptor.max_definition_level:
                return None
            vi = self._ensure_value_index()[i]
        else:
            vi = i
        v = self.values[int(vi)]
        return v

    def dense(self, fill=None):
        """Dense representation: (values_with_fill, null_mask) arrays.

        Fixed-width types get a NumPy array with ``fill`` (or 0) in null
        slots; BYTE_ARRAY gets a ByteArrayColumn with empty strings at null
        slots.  This is the array that ships to the TPU.
        """
        mask = self.null_mask
        if mask is None:
            return self.values, None
        n = self.num_values
        if isinstance(self.values, ByteArrayColumn):
            lengths = np.zeros(n, dtype=np.int64)
            lengths[~mask] = self.values.lengths()
            offsets = np.zeros(n + 1, dtype=np.int64)
            np.cumsum(lengths, out=offsets[1:])
            return ByteArrayColumn(offsets, self.values.data.copy()), mask
        if self.values.ndim == 2:  # FLBA / INT96 rows
            out = np.zeros((n, self.values.shape[1]), dtype=self.values.dtype)
            out[~mask] = self.values
            return out, mask
        out = np.zeros(n, dtype=self.values.dtype)
        if fill is not None:
            out[:] = fill
        out[~mask] = self.values
        return out, mask


@dataclass
class RowGroupBatch:
    """Decoded columns of one row group, in schema (column) order."""

    columns: List[ColumnBatch]
    num_rows: int

    def column(self, top_level_name: str) -> ColumnBatch:
        for c in self.columns:
            if c.descriptor.path[0] == top_level_name:
                return c
        raise KeyError(f"no column with top-level name {top_level_name!r}")
