"""L3: columnar batch materialization (SURVEY.md §7 `batch/`)."""
