"""Generate the parquet-mr-convention golden files in tests/data/golden/.

The reference's writer emits parquet-mr 1.12.2 bytes (SNAPPY +
PARQUET_2_0 pinned through parquet-mr, reference ParquetWriter.java:65-66,
pom.xml:52-69), so parquet-mr output conventions are the compatibility
bar this repo inherits.  This image has no JVM, so true parquet-mr bytes
cannot be produced offline (documented in tests/data/golden/README.md);
instead this script assembles files that reproduce parquet-mr's output
conventions at the byte-format level — conventions this repo's OWN
writer never produces, so reading them is a genuine third-party
compatibility check:

  * ``mr_legacy_2level_list.parquet`` — the legacy 2-level LIST schema
    (``optional group v (LIST) { repeated int32 array; }``) parquet-mr/
    Spark wrote before the 3-level standard, v1 pages, RLE levels.
  * ``mr_bitpacked_levels.parquet`` — v1 page with deprecated MSB-first
    BIT_PACKED definition levels (very old parquet-mr files).
  * ``mr_int96_dict_gzip.parquet`` — INT96 timestamps, PLAIN_DICTIONARY
    dictionary+data pages (the legacy encoding id parquet-mr v1 stamps,
    where this repo's writer emits RLE_DICTIONARY), GZIP.
  * ``mr_v2_delta_snappy.parquet`` — the reference writer's pinned
    SNAPPY + PARQUET_2_0 shape: v2 pages, DELTA_BINARY_PACKED ints,
    DELTA_BYTE_ARRAY strings, ConvertedType-only UTF8 annotation.

Every file is built from low-level format primitives (thrift structs +
encoders), stamped with parquet-mr 1.12.2's created_by, and validated
against the pyarrow oracle before being written.  The binaries are
checked in; re-running the script must be deterministic.

Usage: python scripts/make_golden.py  (writes tests/data/golden/, validates)
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from parquet_floor_tpu.format import codecs
from parquet_floor_tpu.format.encodings.delta import (
    encode_delta_binary_packed,
    encode_delta_byte_array,
)
from parquet_floor_tpu.format.encodings.dictionary import encode_dict_indices
from parquet_floor_tpu.format.encodings.plain import (
    ByteArrayColumn,
    encode_plain,
)
from parquet_floor_tpu.format.encodings.rle_hybrid import (
    encode_length_prefixed,
    encode_rle_hybrid,
)
from parquet_floor_tpu.format.metadata import MAGIC, serialize_footer
from parquet_floor_tpu.format.parquet_thrift import (
    ColumnChunk,
    ColumnIndex,
    ColumnMetaData,
    CompressionCodec,
    ConvertedType,
    DataPageHeader,
    DataPageHeaderV2,
    DictionaryPageHeader,
    Encoding,
    FieldRepetitionType,
    FileMetaData,
    OffsetIndex,
    PageHeader,
    PageLocation,
    PageType,
    RowGroup,
    SchemaElement,
    Type,
)

CREATED_BY = (
    "parquet-mr version 1.12.2 "
    "(build db75a6815f2ba1d1ee89d1a90aeb296f1f3a8f20)"
)
GOLDEN_DIR = os.path.join(
    os.path.dirname(__file__), "..", "tests", "data", "golden"
)


class _Chunk:
    """One column chunk: page bytes + the footer metadata describing it."""

    def __init__(self, path, ptype, pages, encodings, codec, num_values,
                 converted_type=None, has_dict=False):
        self.path = list(path)
        self.ptype = ptype
        self.pages = pages          # list of (header_bytes, payload_bytes)
        self.encodings = encodings
        self.codec = codec
        self.num_values = num_values
        self.converted_type = converted_type
        self.has_dict = has_dict


def _v1_page(payload: bytes, num_values: int, encoding: int, codec: int,
             def_enc: int = Encoding.RLE, rep_enc: int = Encoding.RLE):
    comp = codecs.compress(codec, payload)
    hdr = PageHeader(
        type=PageType.DATA_PAGE,
        uncompressed_page_size=len(payload),
        compressed_page_size=len(comp),
        data_page_header=DataPageHeader(
            num_values=num_values,
            encoding=encoding,
            definition_level_encoding=def_enc,
            repetition_level_encoding=rep_enc,
        ),
    )
    return hdr.to_bytes(), comp


def _v2_page(levels: bytes, values: bytes, num_values: int, num_nulls: int,
             num_rows: int, encoding: int, codec: int,
             def_len: int, rep_len: int):
    comp = codecs.compress(codec, values)
    hdr = PageHeader(
        type=PageType.DATA_PAGE_V2,
        uncompressed_page_size=len(levels) + len(values),
        compressed_page_size=len(levels) + len(comp),
        data_page_header_v2=DataPageHeaderV2(
            num_values=num_values,
            num_nulls=num_nulls,
            num_rows=num_rows,
            encoding=encoding,
            definition_levels_byte_length=def_len,
            repetition_levels_byte_length=rep_len,
            is_compressed=True,
        ),
    )
    return hdr.to_bytes(), levels + comp


def _dict_page(payload: bytes, num_values: int, codec: int,
               encoding: int = Encoding.PLAIN_DICTIONARY):
    comp = codecs.compress(codec, payload)
    hdr = PageHeader(
        type=PageType.DICTIONARY_PAGE,
        uncompressed_page_size=len(payload),
        compressed_page_size=len(comp),
        dictionary_page_header=DictionaryPageHeader(
            num_values=num_values, encoding=encoding
        ),
    )
    return hdr.to_bytes(), comp


def _write_file(path, schema_elements, chunks, num_rows):
    """Assemble one single-row-group file parquet-mr style: no page
    index, no CRCs, no column statistics, created_by stamped 1.12.2."""
    _write_file_multi(path, schema_elements, [chunks], num_rows)


def _write_file_multi(path, schema_elements, groups, rows_per_group):
    """Multi-row-group assembly.  Chunks with a ``column_index``
    attribute also get their ColumnIndex/OffsetIndex appended between
    the data and the footer in parquet-mr's layout (all ColumnIndexes,
    then all OffsetIndexes, offsets recorded in each ColumnChunk)."""
    buf = bytearray(MAGIC)
    rgs = []
    index_jobs = []  # (chunk_struct, ColumnIndex, [(off, size, first_row)])
    for chunks in groups:
        cols = []
        total = 0
        for ch in chunks:
            first_off = len(buf)
            dict_off = first_off if ch.has_dict else None
            comp_total = 0
            unc_total = 0
            locs = []
            first_rows = getattr(ch, "page_first_rows", None)
            for pi, (hdr, payload) in enumerate(ch.pages):
                # dict page (always pages[0] when present) never lands
                # in the OffsetIndex — it locates DATA pages only
                di = pi - (1 if ch.has_dict else 0)
                if first_rows is not None and di >= 0:
                    locs.append(
                        (len(buf), len(hdr) + len(payload), first_rows[di])
                    )
                buf += hdr + payload
                comp_total += len(hdr) + len(payload)
                # header bytes count in both totals, payloads at their
                # uncompressed size (parquet-mr convention)
                ph, _ = PageHeader.from_bytes(hdr)
                unc_total += len(hdr) + ph.uncompressed_page_size
            meta = ColumnMetaData(
                type=ch.ptype,
                encodings=ch.encodings,
                path_in_schema=ch.path,
                codec=ch.codec,
                num_values=ch.num_values,
                total_uncompressed_size=unc_total,
                total_compressed_size=comp_total,
                data_page_offset=(
                    first_off + len(ch.pages[0][0]) + len(ch.pages[0][1])
                    if ch.has_dict else first_off
                ),
                dictionary_page_offset=dict_off,
            )
            cc = ColumnChunk(file_offset=first_off, meta_data=meta)
            cols.append(cc)
            total += comp_total
            if getattr(ch, "column_index", None) is not None:
                index_jobs.append((cc, ch.column_index, locs))
        rgs.append(RowGroup(columns=cols, total_byte_size=total,
                            num_rows=rows_per_group))
    # parquet-mr order: ColumnIndex structs first, then OffsetIndexes
    for cc, ci, _ in index_jobs:
        cc.column_index_offset = len(buf)
        blob = ci.to_bytes()
        cc.column_index_length = len(blob)
        buf += blob
    for cc, _, locs in index_jobs:
        cc.offset_index_offset = len(buf)
        blob = OffsetIndex(page_locations=[
            PageLocation(offset=o, compressed_page_size=s,
                         first_row_index=fr)
            for o, s, fr in locs
        ]).to_bytes()
        cc.offset_index_length = len(blob)
        buf += blob
    fmd = FileMetaData(
        version=1,
        schema=schema_elements,
        num_rows=rows_per_group * len(groups),
        row_groups=rgs,
        created_by=CREATED_BY,
    )
    buf += serialize_footer(fmd)
    with open(path, "wb") as f:
        f.write(bytes(buf))


# ---------------------------------------------------------------------------
# File builders
# ---------------------------------------------------------------------------

def make_legacy_2level_list(path):
    """Legacy 2-level LIST: optional group v (LIST) { repeated int32
    array; } — pre-3-level parquet-mr/Spark convention.  def levels:
    0=list null, 1=list empty, 2=element; elements cannot be null."""
    rows = [[1, 2, 3], None, [], [4], [5, 6, 7, 8]]
    reps, defs, vals = [], [], []
    for row in rows:
        if row is None:
            reps.append(0)
            defs.append(0)
        elif not row:
            reps.append(0)
            defs.append(1)
        else:
            for i, v in enumerate(row):
                reps.append(0 if i == 0 else 1)
                defs.append(2)
                vals.append(v)
    payload = (
        encode_length_prefixed(np.array(reps, np.uint32), 1)
        + encode_length_prefixed(np.array(defs, np.uint32), 2)
        + encode_plain(np.array(vals, np.int32), Type.INT32)
    )
    hdr, comp = _v1_page(payload, len(reps), Encoding.PLAIN,
                         CompressionCodec.UNCOMPRESSED)
    schema = [
        SchemaElement(name="spark_schema", num_children=1),
        SchemaElement(name="v", repetition_type=FieldRepetitionType.OPTIONAL,
                      converted_type=ConvertedType.LIST, num_children=1),
        SchemaElement(name="array", type=Type.INT32,
                      repetition_type=FieldRepetitionType.REPEATED),
    ]
    chunk = _Chunk(["v", "array"], Type.INT32, [(hdr, comp)],
                   [Encoding.PLAIN, Encoding.RLE],
                   CompressionCodec.UNCOMPRESSED, len(reps))
    _write_file(path, schema, [chunk], len(rows))
    return {"v": rows}


def make_bitpacked_levels(path):
    """Deprecated MSB-first BIT_PACKED definition levels in a v1 page
    (very old parquet-mr writers; modern readers must still decode)."""
    n = 100
    rows = [None if i % 3 == 0 else i * 1000 for i in range(n)]
    defs = np.array([0 if r is None else 1 for r in rows], np.uint32)
    present = np.array([r for r in rows if r is not None], np.int64)
    # legacy BIT_PACKED is MSB-first within each byte (parquet-format
    # Encodings.md "bit-packed, deprecated"; parquet-mr packs levels
    # with Packer.BIG_ENDIAN) — np.packbits' default order.  NOTE:
    # arrow/pyarrow decodes these levels LSB-first (its LevelDecoder
    # reuses the hybrid BitReader), so pyarrow CANNOT oracle this file;
    # it is validated against pinned expected values instead, and the
    # divergence is this corpus entry's reason to exist.
    level_bytes = np.packbits(defs.astype(np.uint8)).tobytes()
    payload = level_bytes + encode_plain(present, Type.INT64)
    hdr, comp = _v1_page(payload, n, Encoding.PLAIN,
                         CompressionCodec.UNCOMPRESSED,
                         def_enc=Encoding.BIT_PACKED,
                         rep_enc=Encoding.BIT_PACKED)
    schema = [
        SchemaElement(name="m", num_children=1),
        SchemaElement(name="x", type=Type.INT64,
                      repetition_type=FieldRepetitionType.OPTIONAL),
    ]
    chunk = _Chunk(["x"], Type.INT64, [(hdr, comp)],
                   [Encoding.PLAIN, Encoding.BIT_PACKED],
                   CompressionCodec.UNCOMPRESSED, n)
    _write_file(path, schema, [chunk], n)
    return {"x": rows}


def make_int96_dict_gzip(path):
    """INT96 timestamps through PLAIN_DICTIONARY pages (the legacy
    encoding id parquet-mr v1 stamps on both the dictionary page and
    the data page) under GZIP."""
    # distinct timestamps as (nanos-in-day u64 LE, julian-day u32 LE)
    stamps = [
        (3_600_000_000_000, 2451545),   # 2000-01-01 01:00
        (7_200_000_000_000, 2451545),
        (0, 2451546),
        (43_200_000_000_000, 2451910),  # 2001-01-01 12:00
    ]
    pool = np.zeros((len(stamps), 12), np.uint8)
    for i, (nanos, jd) in enumerate(stamps):
        pool[i, :8] = np.frombuffer(
            int(nanos).to_bytes(8, "little"), np.uint8
        )
        pool[i, 8:] = np.frombuffer(int(jd).to_bytes(4, "little"), np.uint8)
    n = 64
    idx = np.array([i % len(stamps) for i in range(n)], np.uint32)
    dict_payload = encode_plain(pool, Type.INT96)
    dhdr, dcomp = _dict_page(dict_payload, len(stamps),
                             CompressionCodec.GZIP)
    data_payload = encode_dict_indices(idx, len(stamps))
    hdr, comp = _v1_page(data_payload, n, Encoding.PLAIN_DICTIONARY,
                         CompressionCodec.GZIP)
    schema = [
        SchemaElement(name="m", num_children=1),
        SchemaElement(name="ts", type=Type.INT96,
                      repetition_type=FieldRepetitionType.REQUIRED),
    ]
    chunk = _Chunk(["ts"], Type.INT96, [(dhdr, dcomp), (hdr, comp)],
                   [Encoding.PLAIN_DICTIONARY, Encoding.RLE],
                   CompressionCodec.GZIP, n, has_dict=True)
    _write_file(path, schema, [chunk], n)
    # expected: raw 12-byte values per row
    return {"ts": [pool[i % len(stamps)].tobytes() for i in range(n)]}


def make_v2_delta_snappy(path):
    """The reference writer's pinned output shape (SNAPPY + PARQUET_2_0,
    ParquetWriter.java:65-66): v2 pages, DELTA_BINARY_PACKED int64,
    DELTA_BYTE_ARRAY strings, ConvertedType-only UTF8 annotation."""
    n = 500
    ids = (np.arange(n, dtype=np.int64) * 37) % 1000 - 250
    names = [
        None if i % 7 == 0 else f"user-{i % 23:04d}-{i}" for i in range(n)
    ]
    # id: required → no levels
    id_vals = encode_delta_binary_packed(ids)
    id_hdr, id_bytes = _v2_page(
        b"", id_vals, n, 0, n, Encoding.DELTA_BINARY_PACKED,
        CompressionCodec.SNAPPY, 0, 0,
    )
    id_chunk = _Chunk(["id"], Type.INT64, [(id_hdr, id_bytes)],
                      [Encoding.DELTA_BINARY_PACKED],
                      CompressionCodec.SNAPPY, n)
    # name: optional → unframed RLE def levels outside the compressed blob
    defs = np.array([0 if s is None else 1 for s in names], np.uint32)
    lv = encode_rle_hybrid(defs, 1)
    present = [s.encode() for s in names if s is not None]
    col = ByteArrayColumn(
        np.cumsum([0] + [len(s) for s in present]).astype(np.int64),
        np.frombuffer(b"".join(present), np.uint8),
    )
    nm_vals = encode_delta_byte_array(col)
    nm_hdr, nm_bytes = _v2_page(
        lv, nm_vals, n, int((defs == 0).sum()), n,
        Encoding.DELTA_BYTE_ARRAY, CompressionCodec.SNAPPY, len(lv), 0,
    )
    nm_chunk = _Chunk(["name"], Type.BYTE_ARRAY, [(nm_hdr, nm_bytes)],
                      [Encoding.DELTA_BYTE_ARRAY, Encoding.RLE],
                      CompressionCodec.SNAPPY, n,
                      converted_type=ConvertedType.UTF8)
    schema = [
        SchemaElement(name="m", num_children=2),
        SchemaElement(name="id", type=Type.INT64,
                      repetition_type=FieldRepetitionType.REQUIRED),
        SchemaElement(name="name", type=Type.BYTE_ARRAY,
                      repetition_type=FieldRepetitionType.OPTIONAL,
                      converted_type=ConvertedType.UTF8),
    ]
    _write_file(path, schema, [id_chunk, nm_chunk], n)
    return {"id": ids.tolist(), "name": names}


def make_pageindex_bss_lz4(path):
    """parquet-mr 1.12 writes the page index BY DEFAULT — this file has
    ColumnIndex + OffsetIndex (the only corpus entry that does), two
    row groups, BYTE_STREAM_SPLIT floats and an optional PLAIN INT32,
    all under parquet's legacy Hadoop-framed LZ4.  The float pages are
    VALUE-DISJOINT (page p of group g spans [g*10000+p*1000,
    +100) plus fraction) so ColumnIndex min/max page pruning is
    testable against them."""
    from parquet_floor_tpu.format.encodings.byte_stream_split import (
        encode_byte_stream_split,
    )

    rng = np.random.default_rng(17)
    groups = []
    expected_f: list = []
    expected_o: list = []
    for g in range(2):
        f_vals = (
            g * 10_000
            + np.repeat(np.arange(3), 100) * 1000
            + np.tile(np.arange(100), 3)
            + np.round(rng.random(300), 3)
        ).astype(np.float32)
        o_vals = [
            None if i % 5 == g else int(i + 1000 * g) for i in range(300)
        ]
        expected_f.extend(float(v) for v in f_vals)
        expected_o.extend(o_vals)
        # f: 3 pages of 100 values, BYTE_STREAM_SPLIT + LZ4(hadoop)
        f_pages, f_locs, f_mins, f_maxs = [], [], [], []
        for p in range(3):
            chunk_vals = f_vals[p * 100 : (p + 1) * 100]
            payload = encode_byte_stream_split(chunk_vals)
            hdr, comp = _v1_page(payload, 100, Encoding.BYTE_STREAM_SPLIT,
                                 CompressionCodec.LZ4)
            f_pages.append((hdr, comp))
            f_locs.append(p * 100)
            f_mins.append(np.float32(chunk_vals.min()).tobytes())
            f_maxs.append(np.float32(chunk_vals.max()).tobytes())
        fc = _Chunk(["f"], Type.FLOAT, f_pages,
                    [Encoding.BYTE_STREAM_SPLIT, Encoding.RLE],
                    CompressionCodec.LZ4, 300)
        fc.page_first_rows = f_locs
        fc.column_index = ColumnIndex(
            null_pages=[False] * 3, min_values=f_mins, max_values=f_maxs,
            boundary_order=0, null_counts=[0, 0, 0],
        )
        # o: optional INT32, single page, RLE def levels
        defs = np.array([0 if v is None else 1 for v in o_vals], np.uint32)
        present = np.array([v for v in o_vals if v is not None], np.int32)
        payload = (
            encode_length_prefixed(defs, 1)
            + encode_plain(present, Type.INT32)
        )
        hdr, comp = _v1_page(payload, 300, Encoding.PLAIN,
                             CompressionCodec.LZ4)
        oc = _Chunk(["o"], Type.INT32, [(hdr, comp)],
                    [Encoding.PLAIN, Encoding.RLE],
                    CompressionCodec.LZ4, 300)
        oc.page_first_rows = [0]
        oc.column_index = ColumnIndex(
            null_pages=[False],
            min_values=[np.int32(present.min()).tobytes()],
            max_values=[np.int32(present.max()).tobytes()],
            boundary_order=0,
            null_counts=[int((defs == 0).sum())],
        )
        groups.append([fc, oc])
    schema = [
        SchemaElement(name="m", num_children=2),
        SchemaElement(name="f", type=Type.FLOAT,
                      repetition_type=FieldRepetitionType.REQUIRED),
        SchemaElement(name="o", type=Type.INT32,
                      repetition_type=FieldRepetitionType.OPTIONAL),
    ]
    _write_file_multi(path, schema, groups, rows_per_group=300)
    return {"f": expected_f, "o": expected_o}


BUILDERS = {
    "mr_legacy_2level_list.parquet": make_legacy_2level_list,
    "mr_bitpacked_levels.parquet": make_bitpacked_levels,
    "mr_int96_dict_gzip.parquet": make_int96_dict_gzip,
    "mr_v2_delta_snappy.parquet": make_v2_delta_snappy,
    "mr_pageindex_bss_lz4.parquet": make_pageindex_bss_lz4,
}

# Files pyarrow cannot oracle (see the builder's comment for why); they
# are validated against pinned expected values only.
NO_PYARROW_ORACLE = {"mr_bitpacked_levels.parquet"}


def _validate_with_pyarrow(path, expected):
    import pyarrow.parquet as pq

    table = pq.read_table(path)
    for col, want in expected.items():
        got = table.column(col).to_pylist()
        if col == "ts":
            # pyarrow renders INT96 as timestamps; compare as raw bytes
            # via the epoch math (nanos since epoch → julian day/nanos)
            import datetime

            def to_raw(ts):
                ns = int(
                    ts.replace(tzinfo=datetime.timezone.utc).timestamp()
                ) * 1_000_000_000 + ts.microsecond * 1000 + ts.nanosecond
                jd, in_day = divmod(ns + 2440588 * 86400 * 10**9,
                                    86400 * 10**9)
                return int(in_day).to_bytes(8, "little") + int(jd).to_bytes(
                    4, "little"
                )

            got = [to_raw(ts) for ts in got]
        assert got == want, f"{os.path.basename(path)}:{col} mismatch"


def main():
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    import json

    expected_all = {}
    for fname, builder in BUILDERS.items():
        path = os.path.join(GOLDEN_DIR, fname)
        expected = builder(path)
        if fname not in NO_PYARROW_ORACLE:
            _validate_with_pyarrow(path, expected)
            print(f"wrote + pyarrow-validated {fname}")
        else:
            print(f"wrote {fname} (pinned expected values; no pyarrow "
                  "oracle — see builder comment)")
        expected_all[fname] = {
            k: [
                v.hex() if isinstance(v, bytes) else v for v in vals
            ]
            for k, vals in expected.items()
        }
    # expected values land next to the binaries so the test needs no
    # regeneration logic (bytes values hex-encoded)
    with open(os.path.join(GOLDEN_DIR, "expected.json"), "w") as f:
        json.dump(expected_all, f, indent=1, sort_keys=True)
    print("expected.json written")


if __name__ == "__main__":
    main()
