#!/usr/bin/env python
"""Commit-gate remote-scan smoke (docs/remote.md): a seeded
SimulatedRemoteSource dataset scanned twice —

1. a clean 20 ms-RTT pass asserting the scheduled scan actually
   overlaps (``overlap_fraction`` floor), and
2. a fault-heavy pass (outage + heavy tail + throttling + seeded drops)
   asserting the scan COMPLETES, bit-identical to the clean pass, with
   retries, hedges, and breaker trips all on the counters.

Fixed seeds; wall time a few seconds.  Exit 0 on success, 1 with a
diagnostic otherwise.
"""

from __future__ import annotations

import os
import pathlib
import sys
import time
import zlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

from parquet_floor_tpu import (  # noqa: E402
    ParquetFileWriter,
    ReaderOptions,
    WriterOptions,
    types,
)
from parquet_floor_tpu.scan import DatasetScanner, ScanOptions  # noqa: E402
from parquet_floor_tpu.testing import (  # noqa: E402
    RemoteProfile,
    SimulatedRemoteSource,
)
from parquet_floor_tpu.utils import trace  # noqa: E402

OVERLAP_FLOOR = 0.3
WORK_S = 0.0022
RTT_S = 0.02


def build_dataset(tmp_dir, n_files=2, groups=8, group_rows=60):
    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.required(types.DOUBLE).named("d"),
    )
    paths = []
    for i in range(n_files):
        p = os.path.join(tmp_dir, f"remote_smoke_{i}.parquet")
        rng = np.random.default_rng(50 + i)
        with ParquetFileWriter(p, schema, WriterOptions(
            row_group_rows=group_rows, data_page_values=group_rows,
        )) as w:
            for lo in range(0, groups * group_rows, group_rows):
                w.write_columns({
                    "k": np.arange(lo, lo + group_rows, dtype=np.int64),
                    "d": rng.standard_normal(group_rows),
                })
        paths.append(p)
    return paths


def scan_digests(paths, profile, retries, **hedge_kw):
    factories = [
        (lambda p=p, i=i: SimulatedRemoteSource(
            p, profile=profile, seed=2000 + i, fetch_threads=4, **hedge_kw
        ))
        for i, p in enumerate(paths)
    ]
    opts = ReaderOptions(io_retries=retries, io_retry_backoff_s=0.04)
    sc = ScanOptions(threads=8, adaptive_prefetch=True)
    digests = []
    with trace.scope() as t:
        t0 = time.perf_counter()
        with DatasetScanner(factories, options=opts, scan=sc) as s:
            for unit in s:
                cols = tuple(
                    zlib.crc32(np.ascontiguousarray(c.values).tobytes())
                    for c in unit.batch.columns
                )
                digests.append(
                    (unit.file_index, unit.group_index,
                     unit.batch.num_rows, cols)
                )
                time.sleep(WORK_S)
        wall = time.perf_counter() - t0
    return digests, t.scan_report(wall_seconds=wall), t.counters()


def main() -> int:
    import tempfile

    tmp = tempfile.mkdtemp(prefix="pftpu_remote_smoke_")
    paths = build_dataset(tmp)

    clean = RemoteProfile(base_latency_s=RTT_S, jitter_s=0.002)
    clean_digests, clean_rep, _ = scan_digests(paths, clean, retries=3)
    if clean_rep.overlap_fraction is None or \
            clean_rep.overlap_fraction < OVERLAP_FLOOR:
        print(f"remote_scan_smoke: FAIL — clean overlap_fraction "
              f"{clean_rep.overlap_fraction} < {OVERLAP_FLOOR}",
              file=sys.stderr)
        return 1

    hostile = RemoteProfile(
        base_latency_s=RTT_S, jitter_s=0.002,
        tail_p=0.2, tail_latency_s=0.08,
        fault_rate=0.08, outage_s=0.25,
        throttle_rps=60, throttle_burst=2,
    )
    fault_digests, _fault_rep, counters = scan_digests(
        paths, hostile, retries=6,
        hedge_delay_s=0.06, breaker_threshold=3, breaker_cooldown_s=0.06,
    )
    if fault_digests != clean_digests:
        print("remote_scan_smoke: FAIL — fault-heavy scan is not "
              "bit-identical to the clean scan", file=sys.stderr)
        return 1
    expected = {
        "io.retries": "retry",
        "io.remote.hedges": "hedge",
        "io.remote.breaker_trips": "breaker-trip",
        "io.remote.throttles": "throttle",
    }
    missing = [
        label for name, label in expected.items()
        if counters.get(name, 0) < 1
    ]
    if missing:
        print(f"remote_scan_smoke: FAIL — fault scan never exercised: "
              f"{missing} (counters: {counters})", file=sys.stderr)
        return 1
    unregistered = set(counters) - trace.names.ALL
    if unregistered:
        print(f"remote_scan_smoke: FAIL — unregistered counters "
              f"{sorted(unregistered)}", file=sys.stderr)
        return 1
    print(
        f"remote_scan_smoke: ok — {len(clean_digests)} units, "
        f"clean overlap {clean_rep.overlap_fraction}, fault scan "
        f"bit-identical with retries={counters.get('io.retries')} "
        f"hedges={counters.get('io.remote.hedges')} "
        f"breaker_trips={counters.get('io.remote.breaker_trips')} "
        f"throttles={counters.get('io.remote.throttles')}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
