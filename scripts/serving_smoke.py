#!/usr/bin/env python
"""Commit-gate serving smoke (docs/serving.md, docs/observability.md).

Seeded, self-contained, CPU-only: builds a small keyed dataset, then
asserts the serving layer's load-bearing floors —

1. **shared-cache hit-rate**: after one tenant's cold scan, two MORE
   tenants scanning the same files CONCURRENTLY are each served almost
   entirely from the shared buffer cache (hit-rate >= 0.5 per tenant,
   from each tenant's OWN report), and their reports stay disjoint
   (each sees exactly one scan's planned bytes);
2. **probe byte-cost**: a hot one-column ``Dataset.lookup`` (metadata
   pinned by the warm pass) reads more than zero and at most ONE data
   page of storage bytes, proven by the cache's miss-byte counters;
3. **live metrics**: ``trace.serve_metrics`` on an ephemeral port,
   scraped MID-RUN — the body must parse as Prometheus text exposition
   (small stdlib parser) and its counter values must match
   ``cache.stats()`` / the tracer's own truth;
4. **per-tenant SLO**: an injected slow tenant (storage reads behind a
   latency shim) must trip a registered ``serve.slo_breach`` decision
   on ITS tracer while a healthy tenant probing the same dataset does
   not — per-tenant p99 from the new histograms, end to end;
5. **one-clock timeline**: ``trace.unified_trace`` around a device
   scan emits a single Perfetto-loadable file whose XLA-capture events
   and host ``ship``/``decode`` spans sit on one rebased clock
   (balanced, monotonic, overlapping time ranges).

Exit 0 on success, 1 with a diagnostic otherwise.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import threading
import time
import urllib.request

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

from parquet_floor_tpu import (  # noqa: E402
    ParquetFileWriter,
    WriterOptions,
    types,
)
from parquet_floor_tpu.serve import (  # noqa: E402
    Dataset,
    Serving,
    SharedBufferCache,
)

GROUP = 256
PAGE = 64
GROUPS = 4
FILES = 2


def build_paths() -> list:
    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.required(types.DOUBLE).named("d"),
    )
    per = GROUP * GROUPS
    paths = []
    for i in range(FILES):
        p = f"/tmp/pftpu_serving_smoke_{per}_{i}.parquet"
        if not os.path.exists(p):
            rng = np.random.default_rng(40 + i)
            with ParquetFileWriter(p, schema, WriterOptions(
                row_group_rows=GROUP, data_page_values=PAGE,
                bloom_filter_columns={"k": True},
            )) as w:
                for lo in range(0, per, GROUP):
                    base = 2 * (i * per + lo)
                    w.write_columns({
                        "k": base + 2 * np.arange(GROUP, dtype=np.int64),
                        "s": [None if j % 9 == 0 else f"s{j % 41}"
                              for j in range(GROUP)],
                        "d": rng.standard_normal(GROUP),
                    })
        paths.append(p)
    return paths


def fail(msg: str) -> int:
    print(f"serving_smoke: FAIL — {msg}", file=sys.stderr)
    return 1


def hit_rate(report) -> float:
    hit = report.counters.get("serve.cache_hit_bytes", 0)
    miss = report.counters.get("serve.cache_miss_bytes", 0)
    return hit / (hit + miss) if hit + miss else 0.0


def main() -> int:
    paths = build_paths()

    with Serving(prefetch_bytes=16 << 20) as srv:
        cold = srv.tenant("cold")

        def scan_rows(tenant):
            rows = 0
            with tenant.scan(paths) as s:
                for unit in s:
                    rows += unit.batch.num_rows
            return rows

        rows = scan_rows(cold)
        if rows != FILES * GROUP * GROUPS:
            return fail(f"cold scan read {rows} rows, expected "
                        f"{FILES * GROUP * GROUPS}")
        warm_a = srv.tenant("warm-a", weight=2)
        warm_b = srv.tenant("warm-b")
        results: dict = {}

        def run(name, tenant):
            results[name] = scan_rows(tenant)

        threads = [
            threading.Thread(target=run, args=("a", warm_a)),
            threading.Thread(target=run, args=("b", warm_b)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if results["a"] != rows or results["b"] != rows:
            return fail(f"concurrent warm scans read {results}, "
                        f"expected {rows} rows each")
        used = cold.report().counters.get("scan.bytes_used", 0)
        for name, tenant in (("warm-a", warm_a), ("warm-b", warm_b)):
            rep = tenant.report()
            rate = hit_rate(rep)
            if not rate >= 0.5:
                return fail(f"{name} hit-rate {rate:.3f} < 0.5 on the "
                            "warm concurrent pass")
            if rep.counters.get("scan.bytes_used", 0) != used:
                return fail(f"{name}'s report is not attributed to one "
                            "scan (bytes_used "
                            f"{rep.counters.get('scan.bytes_used')} != "
                            f"{used})")
            print(f"serving_smoke: {name} hit-rate {rate:.3f}, "
                  f"bytes_used {used} (disjoint)")

    # -- probe byte-cost floor (its own cache: nothing pre-populated) ----
    per = GROUP * GROUPS
    with SharedBufferCache() as cache:
        with Dataset(paths, "k", cache=cache) as ds:
            ds.lookup(0)  # warm: opens every file, pins probe metadata
            bound = ds.page_size_bound()
            s0 = cache.stats()
            hot = ds.lookup(2 * (FILES * per - 1), columns=["k"])
            s1 = cache.stats()
            cost = s1["miss_bytes"] - s0["miss_bytes"]
            if len(hot) != 1:
                return fail(f"hot lookup returned {len(hot)} rows, "
                            "expected exactly 1")
            if not 0 < cost <= bound:
                return fail(f"hot one-column lookup cost {cost} storage "
                            f"bytes (one-page bound {bound})")
            print(f"serving_smoke: hot lookup cost {cost} B <= one-page "
                  f"bound {bound} B")

    rc = check_metrics_endpoint(paths)
    if rc:
        return rc
    rc = check_slo_breach(paths)
    if rc:
        return rc
    rc = check_unified_trace(paths)
    if rc:
        return rc
    print("serving_smoke: PASS")
    return 0


# -- live metrics endpoint (docs/observability.md) -----------------------

def validate_prometheus_text(text: str) -> dict:
    """Validate one scrape: sample extraction rides the library's own
    ``parse_prometheus`` (one grammar, one implementation —
    docs/observability.md); this layers the structural checks a scrape
    consumer cares about — every sample family carries a TYPE
    declaration, and histogram families are internally consistent
    (the ``+Inf`` bucket equals ``_count``).  Returns {sample name ->
    value}; raises on violation."""
    import re

    from parquet_floor_tpu.utils.metrics_export import parse_prometheus

    samples = parse_prometheus(text)   # raises on malformed lines
    typed = {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            fam, _, kind = line[len("# TYPE "):].partition(" ")
            if kind not in ("counter", "gauge", "histogram", "summary",
                            "untyped"):
                raise ValueError(f"bad TYPE line: {line!r}")
            typed[fam] = kind
    for sample in samples:
        name = sample.split("{")[0]
        fam = re.sub(r"_(bucket|sum|count)$", "", name)
        if name not in typed and fam not in typed:
            raise ValueError(f"sample {name!r} has no TYPE declaration")
    # histogram families: _count present and equal to the +Inf bucket
    for fam, kind in typed.items():
        if kind != "histogram":
            continue
        count = samples.get(f"{fam}_count")
        inf = samples.get(f'{fam}_bucket{{le="+Inf"}}')
        if count is None or inf is None or count != inf:
            raise ValueError(
                f"histogram {fam}: _count {count} != +Inf bucket {inf}"
            )
    return samples


def check_metrics_endpoint(paths) -> int:
    """Floor 3: scrape ``trace.serve_metrics`` mid-run; the text must
    validate and its counters must equal cache/tracer truth."""
    from parquet_floor_tpu.utils import trace

    with SharedBufferCache() as cache, trace.scope() as t:
        with Dataset(paths, "k", cache=cache) as ds:
            server = trace.serve_metrics(0)   # ephemeral port, tracer t
            try:
                ds.lookup(0)
                # mid-run scrape: the endpoint serves while probes run
                mid = urllib.request.urlopen(
                    server.url(), timeout=10
                ).read().decode()
                validate_prometheus_text(mid)
                ds.lookup(2 * (GROUP * GROUPS), columns=["k"])
                ds.lookup(4, columns=["k"])
                # quiesced scrape: values must MATCH the other truths
                text = urllib.request.urlopen(
                    server.url(), timeout=10
                ).read().decode()
                samples = validate_prometheus_text(text)
                js = json.loads(urllib.request.urlopen(
                    server.url("/metrics.json"), timeout=10
                ).read().decode())
            finally:
                server.close()
            st = cache.stats()
            counters = t.counters()
    for prom, truth, src in (
        ("pftpu_serve_cache_misses", st["misses"], "cache.stats"),
        ("pftpu_serve_cache_miss_bytes", st["miss_bytes"], "cache.stats"),
        ("pftpu_serve_cache_hits", st["hits"], "cache.stats"),
        ("pftpu_serve_lookup_probes",
         counters.get("serve.lookup_probes", 0), "tracer"),
    ):
        got = samples.get(prom)
        if got != truth:
            return fail(f"scrape {prom}={got} != {src} truth {truth}")
    if js.get("counters") != counters:
        return fail("JSON snapshot counters diverge from tracer truth")
    hist_count = samples.get("pftpu_serve_lookup_seconds_count")
    if not hist_count or hist_count != counters.get(
        "serve.lookup_probes", 0
    ):
        return fail(
            f"lookup histogram count {hist_count} != probe counter "
            f"{counters.get('serve.lookup_probes', 0)}"
        )
    print(f"serving_smoke: metrics scrape ok ({len(samples)} samples, "
          f"counters match cache.stats)")
    return 0


# -- per-tenant SLO breach (docs/serving.md) ------------------------------

class _SlowSource:
    """A FileSource behind an injected per-read storage latency — the
    smoke's 'slow tenant' lives behind this shim."""

    def __init__(self, path: str, delay_s: float):
        from parquet_floor_tpu.io.source import FileSource

        self._src = FileSource(path)
        self._delay = float(delay_s)
        self.size = self._src.size
        self.name = self._src.name

    def read_at(self, offset: int, length: int):
        time.sleep(self._delay)
        return self._src.read_at(offset, length)

    def read_many(self, ranges):
        time.sleep(self._delay)
        return self._src.read_many(ranges)

    def close(self) -> None:
        self._src.close()


def check_slo_breach(paths) -> int:
    """Floor 4: the injected-slow tenant trips ``serve.slo_breach``;
    the healthy tenant probing the same keys does not."""
    from parquet_floor_tpu.serve import Serving, SloTarget

    per = GROUP * GROUPS
    # margins sized for noisy CI hosts: the 20 ms storage shim puts
    # EVERY slow probe 4x past the 5 ms bound, while a healthy local
    # probe (sub-ms typical) breaches only if >= 14.4% of them spend
    # 5 ms+ — a real defect, not scheduler jitter
    SHIM_S = 0.020
    target = SloTarget(
        p99_seconds=0.005,
        fast_window_s=60.0,
        slow_window_s=300.0,
    )
    with Serving(prefetch_bytes=8 << 20) as srv:
        slow = srv.tenant("slow")
        healthy = srv.tenant("healthy")
        srv.set_slo("slow", target)
        srv.set_slo("healthy", target)
        now = 1000.0
        st0 = srv.check_slos(now=now)
        if st0["slow"].breach or st0["healthy"].breach:
            return fail("SLO breached before any traffic")
        with Dataset(
            [(lambda p=p: _SlowSource(p, SHIM_S)) for p in paths], "k",
            cache=srv.cache,
        ) as slow_ds, Dataset(paths, "k", cache=srv.cache) as fast_ds:
            # warm both (opens files, pins metadata — not measured)
            slow_ds.lookup(0)
            fast_ds.lookup(0)
            # 24 probes each, distinct keys -> distinct DATA pages, so
            # every slow probe pays >= one shimmed storage read
            for i in range(24):
                key = 2 * (i * PAGE + (PAGE // 2))
                slow_ds.lookup(key, columns=["k"], tenant=slow)
            for i in range(24):
                key = 2 * (per + i * PAGE + (PAGE // 2))
                fast_ds.lookup(key, columns=["k"], tenant=healthy)
            statuses = srv.check_slos(now=now + 30.0)
        s_slow, s_fast = statuses["slow"], statuses["healthy"]
        if not s_slow.breach:
            return fail(f"slow tenant did not breach: {s_slow.render()}")
        if s_fast.breach:
            return fail(f"healthy tenant breached: {s_fast.render()}")
        breaches = [d for d in slow.tracer.decisions()
                    if d.get("decision") == "serve.slo_breach"]
        if not breaches:
            return fail("no serve.slo_breach decision on the slow "
                        "tenant's tracer")
        if any(d.get("decision") == "serve.slo_breach"
               for d in healthy.tracer.decisions()):
            return fail("spurious serve.slo_breach on the healthy tenant")
        print(f"serving_smoke: slo ok (slow {s_slow.render()} | "
              f"healthy {s_fast.render()})")
        print(srv.health(now=now + 31.0))
    return 0


# -- the one-clock host+device timeline (docs/observability.md) -----------

def check_unified_trace(paths) -> int:
    """Floor 5: one ``unified_trace`` file, balanced + monotonic, with
    host ``ship``/``decode`` spans AND XLA-capture events on one
    rebased clock (overlapping time ranges)."""
    import tempfile

    import jax

    jax.config.update("jax_enable_x64", True)  # INT64/DOUBLE columns
    fd, out_path = tempfile.mkstemp(prefix="pftpu_unified_",
                                    suffix=".json")
    os.close(fd)
    log_dir = tempfile.mkdtemp(prefix="pftpu_xprof_")
    try:
        return _check_unified_trace(paths, out_path, log_dir)
    finally:
        # failure paths must not litter /tmp on every smoke run
        import shutil

        shutil.rmtree(log_dir, ignore_errors=True)
        try:
            os.unlink(out_path)
        except OSError:
            pass


def _check_unified_trace(paths, out_path, log_dir) -> int:
    from parquet_floor_tpu.scan import scan_device_groups
    from parquet_floor_tpu.utils import trace

    with trace.scope():
        with trace.unified_trace(log_dir, out_path) as handle:
            rows = 0
            for _fi, _gi, cols in scan_device_groups(paths):
                col = next(iter(cols.values()))
                rows += int(col.values.shape[0])
    if rows != FILES * GROUP * GROUPS:
        return fail(f"device scan under unified_trace read {rows} rows")
    data = json.loads(pathlib.Path(out_path).read_text())
    events = data.get("traceEvents") or []
    stacks: dict = {}
    last_ts = None
    host_spans = set()
    xla_events = 0
    host_range = [None, None]
    dev_range = [None, None]
    for ev in events:
        if ev.get("ph") == "M":
            continue
        ts = ev["ts"]
        if last_ts is not None and ts < last_ts:
            return fail("unified trace timestamps are not monotonic")
        last_ts = ts
        if ev.get("cat") == "xla":
            xla_events += 1
            dev_range[0] = ts if dev_range[0] is None else dev_range[0]
            dev_range[1] = ts + ev.get("dur", 0.0)
            continue
        key = (ev.get("pid"), ev.get("tid"))
        if ev["ph"] == "B":
            stacks.setdefault(key, []).append(ev["name"])
            host_spans.add(ev["name"])
            host_range[0] = ts if host_range[0] is None else host_range[0]
            host_range[1] = ts
        elif ev["ph"] == "E":
            if not stacks.get(key):
                return fail(f"unbalanced E event on {key}")
            stacks[key].pop()
            host_range[1] = ts
    if any(s for s in stacks.values()):
        return fail(f"unclosed host spans: {stacks}")
    if handle.device_events == 0 or xla_events == 0:
        return fail("unified trace carries no device-origin events")
    if not {"ship", "decode"} <= host_spans:
        return fail(f"unified trace misses host pipeline spans "
                    f"(saw {sorted(host_spans)})")
    if None in host_range or None in dev_range:
        return fail("unified trace missing a time range")
    if not (dev_range[0] < host_range[1]
            and host_range[0] < dev_range[1]):
        return fail(
            f"host {host_range} and device {dev_range} ranges do not "
            "overlap — the clock rebase is wrong"
        )
    print(f"serving_smoke: unified trace ok ({len(events)} events, "
          f"{xla_events} device-origin, host+device ranges overlap)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
