#!/usr/bin/env python
"""Commit-gate serving smoke (docs/serving.md).

Seeded, self-contained, CPU-only: builds a small keyed dataset, then
asserts the serving layer's two load-bearing floors —

1. **shared-cache hit-rate**: after one tenant's cold scan, two MORE
   tenants scanning the same files CONCURRENTLY are each served almost
   entirely from the shared buffer cache (hit-rate >= 0.5 per tenant,
   from each tenant's OWN report), and their reports stay disjoint
   (each sees exactly one scan's planned bytes);
2. **probe byte-cost**: a hot one-column ``Dataset.lookup`` (metadata
   pinned by the warm pass) reads more than zero and at most ONE data
   page of storage bytes, proven by the cache's miss-byte counters.

Exit 0 on success, 1 with a diagnostic otherwise.
"""

from __future__ import annotations

import os
import pathlib
import sys
import threading

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

from parquet_floor_tpu import (  # noqa: E402
    ParquetFileWriter,
    WriterOptions,
    types,
)
from parquet_floor_tpu.serve import (  # noqa: E402
    Dataset,
    Serving,
    SharedBufferCache,
)

GROUP = 256
PAGE = 64
GROUPS = 4
FILES = 2


def build_paths() -> list:
    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.required(types.DOUBLE).named("d"),
    )
    per = GROUP * GROUPS
    paths = []
    for i in range(FILES):
        p = f"/tmp/pftpu_serving_smoke_{per}_{i}.parquet"
        if not os.path.exists(p):
            rng = np.random.default_rng(40 + i)
            with ParquetFileWriter(p, schema, WriterOptions(
                row_group_rows=GROUP, data_page_values=PAGE,
                bloom_filter_columns={"k": True},
            )) as w:
                for lo in range(0, per, GROUP):
                    base = 2 * (i * per + lo)
                    w.write_columns({
                        "k": base + 2 * np.arange(GROUP, dtype=np.int64),
                        "s": [None if j % 9 == 0 else f"s{j % 41}"
                              for j in range(GROUP)],
                        "d": rng.standard_normal(GROUP),
                    })
        paths.append(p)
    return paths


def fail(msg: str) -> int:
    print(f"serving_smoke: FAIL — {msg}", file=sys.stderr)
    return 1


def hit_rate(report) -> float:
    hit = report.counters.get("serve.cache_hit_bytes", 0)
    miss = report.counters.get("serve.cache_miss_bytes", 0)
    return hit / (hit + miss) if hit + miss else 0.0


def main() -> int:
    paths = build_paths()

    with Serving(prefetch_bytes=16 << 20) as srv:
        cold = srv.tenant("cold")

        def scan_rows(tenant):
            rows = 0
            with tenant.scan(paths) as s:
                for unit in s:
                    rows += unit.batch.num_rows
            return rows

        rows = scan_rows(cold)
        if rows != FILES * GROUP * GROUPS:
            return fail(f"cold scan read {rows} rows, expected "
                        f"{FILES * GROUP * GROUPS}")
        warm_a = srv.tenant("warm-a", weight=2)
        warm_b = srv.tenant("warm-b")
        results: dict = {}

        def run(name, tenant):
            results[name] = scan_rows(tenant)

        threads = [
            threading.Thread(target=run, args=("a", warm_a)),
            threading.Thread(target=run, args=("b", warm_b)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if results["a"] != rows or results["b"] != rows:
            return fail(f"concurrent warm scans read {results}, "
                        f"expected {rows} rows each")
        used = cold.report().counters.get("scan.bytes_used", 0)
        for name, tenant in (("warm-a", warm_a), ("warm-b", warm_b)):
            rep = tenant.report()
            rate = hit_rate(rep)
            if not rate >= 0.5:
                return fail(f"{name} hit-rate {rate:.3f} < 0.5 on the "
                            "warm concurrent pass")
            if rep.counters.get("scan.bytes_used", 0) != used:
                return fail(f"{name}'s report is not attributed to one "
                            "scan (bytes_used "
                            f"{rep.counters.get('scan.bytes_used')} != "
                            f"{used})")
            print(f"serving_smoke: {name} hit-rate {rate:.3f}, "
                  f"bytes_used {used} (disjoint)")

    # -- probe byte-cost floor (its own cache: nothing pre-populated) ----
    per = GROUP * GROUPS
    with SharedBufferCache() as cache:
        with Dataset(paths, "k", cache=cache) as ds:
            ds.lookup(0)  # warm: opens every file, pins probe metadata
            bound = ds.page_size_bound()
            s0 = cache.stats()
            hot = ds.lookup(2 * (FILES * per - 1), columns=["k"])
            s1 = cache.stats()
            cost = s1["miss_bytes"] - s0["miss_bytes"]
            if len(hot) != 1:
                return fail(f"hot lookup returned {len(hot)} rows, "
                            "expected exactly 1")
            if not 0 < cost <= bound:
                return fail(f"hot one-column lookup cost {cost} storage "
                            f"bytes (one-page bound {bound})")
            print(f"serving_smoke: hot lookup cost {cost} B <= one-page "
                  f"bound {bound} B")
    print("serving_smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
