#!/usr/bin/env python
"""Commit-gate distributed-tracing smoke (docs/observability.md).

The fleet-trace laws, proven over real sockets — three in-process
``ServeDaemon``\\ s, each mounting a :class:`FleetCache`, every request
under an ambient :func:`trace.start_trace`:

1. **context crosses the wire**: a traced ``read_through`` whose range
   is owned by a PEER must land a ``serve.fleet_serve`` span in the
   owner daemon's flight ring carrying the asker's trace_id, and a
   traced ``DaemonClient`` request must land a ``serve.daemon_request``
   span whose parent is the client-side span;
2. **the merged timeline is one causal chain**: folding every daemon's
   flight ring through :func:`trace.merge_fleet_trace` must yield a
   Perfetto timeline where at least one trace spans two or more hosts,
   every parent link resolves inside its trace, every per-(host,
   thread) track is balanced and time-ordered, AND at least one span's
   parent lives on a DIFFERENT host (the cross-host edge itself);
3. **the flight recorder dumps on demand**: one ``trace.flight_fire``
   must produce an incident bundle whose ``timeline.json`` passes the
   same verification — the bundle a real SLO burn / breaker trip /
   epoch fence would leave behind.

Exit 0 on success, 1 with a diagnostic otherwise.
"""

from __future__ import annotations

import json
import pathlib
import sys
import tempfile
import threading
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from parquet_floor_tpu.serve import (  # noqa: E402
    DaemonClient,
    FleetCache,
    FleetMembership,
    ServeDaemon,
    Serving,
)
from parquet_floor_tpu.utils import trace  # noqa: E402

NODES = ["n0", "n1", "n2"]
RANGES = [(i * 4096, 768) for i in range(24)]
KEY = ("fleet-trace-smoke", 1 << 20)


def fail(msg: str) -> int:
    print(f"fleet_trace_smoke: FAIL {msg}", file=sys.stderr)
    return 1


def content(offset: int, length: int) -> bytes:
    pat = f"smoke:{offset}:{length}:".encode("ascii")
    return (pat * (length // len(pat) + 1))[:length]


def cross_host_edge(merged: dict):
    """A (child_node, parent_node) pair where a span's parent lives on
    a different host — the wire hop itself — or None."""
    node_of = {}
    for e in merged.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "process_name":
            node_of[e.get("pid")] = (e.get("args") or {}).get("name")
    span_node = {}
    for e in merged.get("traceEvents", []):
        a = e.get("args") or {}
        if e.get("ph") == "X" and a.get("span_id"):
            span_node[a["span_id"]] = node_of.get(e.get("pid"))
    for e in merged.get("traceEvents", []):
        a = e.get("args") or {}
        p = a.get("parent_id")
        if e.get("ph") == "X" and p in span_node:
            child = node_of.get(e.get("pid"))
            if span_node[p] != child:
                return (child, span_node[p])
    return None


def main() -> int:
    origin_lock = threading.Lock()

    def origin_read(key, ranges):
        with origin_lock:
            time.sleep(0.001)
        return [content(o, n) for (o, n) in ranges]

    membership = FleetMembership.create(NODES)
    tracer = trace.Tracer(enabled=True)
    with tempfile.TemporaryDirectory() as metrics_dir, \
            tempfile.TemporaryDirectory() as flight_dir:
        servings, fleets, daemons = [], [], []
        try:
            for nid in NODES:
                srv = Serving(prefetch_bytes=4 << 20)
                fc = FleetCache(
                    nid, membership, origin=origin_read,
                    peer_timeout_s=1.0, breaker_threshold=2,
                    breaker_cooldown_s=0.2,
                )
                d = ServeDaemon(
                    srv, {}, fleet=fc, max_inflight=4, max_pending=32,
                    metrics_dir=metrics_dir, flight_dir=flight_dir,
                    flight_debounce_s=0.0, drain_timeout_s=2.0,
                )
                d.start()
                servings.append(srv)
                fleets.append(fc)
                daemons.append(d)
            peers = {nid: ("127.0.0.1", d.port)
                     for nid, d in zip(NODES, daemons)}
            for fc in fleets:
                fc.install_membership(membership, peers)
            daemon_by = dict(zip(NODES, daemons))

            # -- law 1: context crosses the wire ------------------------
            # every node reads every range: non-owned ranges force the
            # peer hop, each under one ambient trace whose client-side
            # spans land in the ASKER's flight ring
            trace_ids = []
            for nid, fc in zip(NODES, fleets):
                with trace.using(tracer), \
                        trace.use_flight_recorder(daemon_by[nid]._flight), \
                        trace.start_trace("smoke_read",
                                          attrs={"node": nid}):
                    trace_ids.append(trace.current_context().trace_id)
                    got = fc.read_through(
                        KEY, RANGES, lambda rs: origin_read(KEY, rs))
                for (o, n), data in zip(RANGES, got):
                    if data != content(o, n):
                        return fail(f"wrong bytes for range {(o, n)}")
            hop_nodes = set()
            for nid, d in zip(NODES, daemons):
                for tr in d._flight.traces():
                    for sp in tr["spans"]:
                        if sp["name"] == "serve.fleet_serve" and \
                                sp["trace_id"] in trace_ids:
                            hop_nodes.add(nid)
            if not hop_nodes:
                return fail("no peer hop carried a trace_id into any "
                            "owner daemon's flight ring")
            # socket propagation through the DaemonClient front door
            with DaemonClient("127.0.0.1", daemons[0].port,
                              tenant="smoke") as client, \
                    trace.using(tracer), \
                    trace.use_flight_recorder(daemons[0]._flight), \
                    trace.start_trace("smoke_lookup") as h:
                tid = trace.current_context().trace_id
                client.request("lookup", dataset="none", key=1)
            daemon_spans = [
                sp
                for tr in daemons[0]._flight.traces()
                if tr["trace_id"] == tid
                for sp in tr["spans"]
            ]
            srv_span = next(
                (s for s in daemon_spans
                 if s["name"] == "serve.daemon_request"), None)
            cli_span = next(
                (s for s in daemon_spans
                 if s["name"] == "serve.client_request"), None)
            if srv_span is None or cli_span is None:
                return fail(
                    "DaemonClient round trip left no client+daemon "
                    f"span pair: {[s['name'] for s in daemon_spans]}")
            if srv_span["parent_id"] != cli_span["span_id"]:
                return fail("daemon_request's parent is not the "
                            "client_request span")
            if srv_span.get("tenant") != "smoke":
                return fail("tenant attribution lost across the socket: "
                            f"{srv_span.get('tenant')!r}")
            print(f"fleet_trace_smoke: propagation ok (peer hops into "
                  f"{sorted(hop_nodes)}, socket parent link + tenant)")

            # -- law 2: one causal chain on one time axis ---------------
            snaps = [d.worker_snapshot() for d in daemons]
            merged = trace.merge_fleet_trace(snaps)
            v = trace.verify_fleet_timeline(merged)
            if not v["span_events"]:
                return fail("merged timeline holds no spans")
            if not v["cross_node_traces"]:
                return fail("no trace spans two hosts in the merge")
            if not v["parent_links_ok"]:
                return fail(f"{v['dangling_parents']} dangling parent "
                            "link(s) in the merged timeline")
            if not v["balanced_ok"]:
                return fail("merged timeline has an unbalanced event")
            if not v["monotonic_ok"]:
                return fail("a (host, thread) track is not time-ordered "
                            "after clock-offset rebasing")
            edge = cross_host_edge(merged)
            if edge is None:
                return fail("no span's parent lives on another host — "
                            "the cross-host edge is missing")
            print(f"fleet_trace_smoke: timeline ok "
                  f"({v['span_events']} spans, {v['tracks']} tracks, "
                  f"{len(v['cross_node_traces'])} cross-host trace(s), "
                  f"edge {edge[1]} -> {edge[0]})")

            # -- law 3: the flight recorder dumps -----------------------
            fired = trace.flight_fire("smoke_test", {"by": "smoke"})
            if fired < len(daemons) * 2:
                return fail(f"flight_fire reached {fired} subscribers, "
                            f"expected >= {len(daemons) * 2}")
            bundles = sorted(pathlib.Path(flight_dir).glob("incident-*"))
            if not bundles:
                return fail("flight_fire produced no incident bundle")
            bundle = bundles[-1]
            for name in ("meta.json", "traces.json", "timeline.json",
                         "metrics.json", "health.txt"):
                if not (bundle / name).exists():
                    return fail(f"bundle misses {name}: {bundle}")
            tl = json.loads((bundle / "timeline.json").read_text())
            bv = trace.verify_fleet_timeline(tl)
            if not bv["ok"] or not bv["cross_node_traces"]:
                return fail(f"bundle timeline fails verification: {bv}")
            meta = json.loads((bundle / "meta.json").read_text())
            if meta.get("reason") != "smoke_test":
                return fail(f"bundle meta carries wrong reason: {meta}")
            print(f"fleet_trace_smoke: flight dump ok "
                  f"({len(bundles)} bundle(s), "
                  f"{bv['span_events']} spans in {bundle.name})")
            print("fleet_trace_smoke: PASS")
            return 0
        finally:
            for d in daemons:
                d.close()
            for fc in fleets:
                fc.close()
            for srv in servings:
                srv.close()


if __name__ == "__main__":
    sys.exit(main())
