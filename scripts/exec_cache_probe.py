#!/usr/bin/env python
"""One cold- or warm-start measurement for the persistent executable
cache (docs/perf.md) — the subprocess half of bench.py's exec-cache leg.

Usage: exec_cache_probe.py PARQUET_FILE CACHE_DIR

Decodes row group 0 of ``PARQUET_FILE`` through the TPU engine with
``PFTPU_EXEC_CACHE=CACHE_DIR`` and prints ONE JSON line::

    {"first_group_wall_ms": ..., "compile_ms": ..., "exec_cache_hits": ...,
     "exec_cache_misses": ..., "launches": ..., "digest": ...}

Run it twice from fresh processes against the same cache dir and the
first run is the COLD measurement (compile + store), the second the
WARM one (deserialize, no compile).  ``digest`` is a CRC of every
decoded array — the two runs must match bit-for-bit (the cache must
never change results, only when compilation happens).
"""

import json
import os
import sys
import time
import zlib

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv) -> int:
    if len(argv) != 3:
        print("usage: exec_cache_probe.py PARQUET_FILE CACHE_DIR",
              file=sys.stderr)
        return 2
    path, cache_dir = argv[1], argv[2]
    os.environ["PFTPU_EXEC_CACHE"] = cache_dir
    # the probe measures the DISPATCH-path resolution (memory → disk →
    # compile); the eager background preload would deserialize the same
    # entry on a second thread concurrently, contending with the timed
    # wall without changing what is measured — keep the measurement
    # clean (preload has its own tests and accounting)
    os.environ.setdefault("PFTPU_EXEC_CACHE_PRELOAD", "0")

    import numpy as np

    import jax

    jax.config.update("jax_enable_x64", True)
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader
    from parquet_floor_tpu.utils import trace

    with trace.scope() as t:
        with TpuRowGroupReader(path, float64_policy="bits") as tr:
            t0 = time.perf_counter()
            cols = tr.read_row_group(0)
            jax.block_until_ready([c.values for c in cols.values()])
            wall = time.perf_counter() - t0
            digest = 0
            for name in sorted(cols):
                c = cols[name]
                for a in (c.values, c.mask, c.lengths):
                    if a is not None:
                        digest = zlib.crc32(
                            np.ascontiguousarray(np.asarray(a)).tobytes(),
                            digest,
                        )
    counters = t.counters()
    print(json.dumps({
        "first_group_wall_ms": round(wall * 1e3, 1),
        "compile_ms": counters.get("engine.compile_ms", 0),
        "exec_cache_hits": counters.get("engine.exec_cache_hits", 0),
        "exec_cache_misses": counters.get("engine.exec_cache_misses", 0),
        "launches": counters.get("engine.launches", 0),
        "digest": digest,
        # the resolution trail (hit / miss / corrupt_entry / …): what a
        # failing cold/warm assertion needs to be diagnosable from logs
        "decisions": [
            d for d in t.decisions()
            if d.get("decision") == "engine.exec_cache"
        ],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
