#!/usr/bin/env python
"""Seeded salvage-differential smoke — the check.sh gate for ISSUE 6's
tentpole part (d) at commit-gate scale.

Replays N seeded corruption cases (fixed seeds 0..N-1, so a failure
reproduces by number) through ALL FOUR read faces — sequential host,
host scan, device scan, DataLoader — and asserts the differential
contract from ``parquet_floor_tpu.testing.differential``: unanimous
fatality, identical quarantine sets, identical surviving bytes, and
no silent divergence vs the clean decode (pyarrow oracle when
installed).  Each case runs under its own SIGALRM time limit, so a
hang is a per-case failure, not a stuck gate.

The >=300-case acceptance sweep lives in
``tests/test_salvage_differential.py`` (``-m slow``); this is the
always-on subset.

Usage: salvage_differential_smoke.py [n_cases] [per_case_timeout_s]
"""

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from parquet_floor_tpu.testing.differential import (  # noqa: E402
    CaseTimeout,
    _pyarrow_clean_groups,
    differential_case,
    write_reference_corpus,
)

FACES = ("sequential", "host_scan", "device_scan", "loader")


def main(argv) -> int:
    import jax

    jax.config.update("jax_enable_x64", True)  # INT64/DOUBLE columns
    n_cases = int(argv[1]) if len(argv) > 1 else 60
    timeout_s = float(argv[2]) if len(argv) > 2 else 30.0
    t0 = time.monotonic()
    fatal = survived = 0
    fails = []
    with tempfile.TemporaryDirectory(prefix="pftpu_diff_") as d:
        corpus = write_reference_corpus(f"{d}/ref")
        oracle = _pyarrow_clean_groups(corpus)
        print(
            f"salvage differential smoke: {n_cases} cases, faces="
            f"{','.join(FACES)}, per-case timeout {timeout_s:.0f}s, "
            f"oracle={'pyarrow' if oracle else 'self'}",
            flush=True,
        )
        for seed in range(n_cases):
            try:
                out = differential_case(
                    corpus, seed, f"{d}/case{seed}", faces=FACES,
                    clean_oracle=oracle, timeout_s=timeout_s,
                )
            except CaseTimeout:
                fails.append((seed, "HANG"))
                print(f"  case {seed}: HANG (> {timeout_s:.0f}s)",
                      flush=True)
                continue
            except AssertionError as e:
                fails.append((seed, str(e)))
                print(f"  case {seed}: DIVERGED: {e}", flush=True)
                continue
            if out.fatal is not None:
                fatal += 1
            else:
                survived += 1
    wall = time.monotonic() - t0
    print(
        f"salvage differential smoke: {n_cases - len(fails)}/{n_cases} "
        f"agree ({survived} salvaged, {fatal} unanimously fatal) "
        f"in {wall:.1f}s",
        flush=True,
    )
    if fails:
        print(f"FAILED cases: {[s for s, _ in fails]}", flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
