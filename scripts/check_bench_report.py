#!/usr/bin/env python
"""Commit-gate validator for the bench smoke's observability artifacts.

``scripts/check.sh`` runs the bench smoke with ``PFTPU_TRACE=1`` and
``PFTPU_TRACE_EXPORT=<path>``; this script then asserts the exported
report actually parses:

1. the bench stdout's JSON line carries a well-formed
   ``detail.scan_report`` (the :class:`ScanReport` health summary), and
2. the Chrome-trace export is loadable trace-event JSON with balanced,
   thread-consistent B/E pairs covering the scan pipeline stages.

Exit 0 when both hold, 1 with a diagnostic otherwise — a broken export
fails the commit gate, not the nightly bench.
"""

from __future__ import annotations

import json
import pathlib
import sys

REPORT_KEYS = (
    "stages", "consumer_stall_seconds", "overlap_fraction",
    "budget_utilization", "bytes_read", "bytes_used", "overread_ratio",
    "retries", "retry_exhausted", "counters", "gauges",
)
SPAN_NAMES = {"read", "stage", "ship", "decode"}


def fail(msg: str) -> int:
    print(f"check_bench_report: {msg}", file=sys.stderr)
    return 1


def _hist_problem(d: dict, require_samples: bool = True):
    """Well-formedness of one serialized LogHistogram: bucket counts
    (plus the zero bucket) must sum to the total count, quantiles must
    be ordered (p50 <= p99), and — on exercised legs — the sample count
    must be nonzero.  Returns a diagnostic string or None."""
    if not isinstance(d, dict):
        return f"not a histogram dict: {d!r}"
    root = str(pathlib.Path(__file__).resolve().parent.parent)
    if root not in sys.path:   # called once per histogram: keep sys.path flat
        sys.path.insert(0, root)
    from parquet_floor_tpu.utils.histogram import LogHistogram

    try:
        h = LogHistogram.from_dict(d)
    except (TypeError, ValueError) as e:
        return f"histogram does not parse: {e}"
    if require_samples and h.count <= 0:
        return "histogram has zero samples on an exercised leg"
    if sum(h.buckets.values()) + h.zeros != h.count:
        return (
            f"bucket counts {sum(h.buckets.values())} + zeros {h.zeros} "
            f"!= count {h.count}"
        )
    if h.count:
        p50, p99 = h.percentile(50), h.percentile(99)
        if not p50 <= p99:
            return f"p50 {p50} > p99 {p99}"
        if h.min is None or h.max is None or h.min > h.max:
            return f"min/max malformed ({h.min}, {h.max})"
    return None


def check_histograms(detail: dict) -> int:
    """The latency-distribution gate (docs/observability.md): every
    histogram the exercised legs exported must be well-formed, and the
    legs that definitionally produced traffic must carry samples — the
    serving leg's lookup + storage-read distributions, the remote fault
    pass's primary-read distribution, and the device scan leg's
    stage/ship/launch walls."""
    required = [
        ("serving_lookup_hist", detail.get("serving_lookup_hist")),
        ("serving_storage_read_hist",
         detail.get("serving_storage_read_hist")),
    ]
    fault_hists = (
        (detail.get("remote_fault_scan_report") or {}).get("histograms")
        or {}
    )
    required.append((
        "remote_fault io.remote.get_seconds.primary",
        fault_hists.get("io.remote.get_seconds.primary"),
    ))
    scan_hists = (detail.get("scan_report") or {}).get("histograms") or {}
    for name in ("engine.stage_seconds", "engine.ship_seconds",
                 "engine.launch_seconds"):
        required.append((f"scan_report {name}", scan_hists.get(name)))
    for label, d in required:
        if d is None:
            return fail(f"exercised leg exported no histogram: {label}")
        problem = _hist_problem(d)
        if problem:
            return fail(f"histogram {label}: {problem}")
    # every OTHER exported histogram must still be well-formed (empty ok)
    for rep_key in ("scan_report", "remote_scan_report",
                    "remote_fault_scan_report", "serving_report"):
        for name, d in ((detail.get(rep_key) or {}).get("histograms")
                        or {}).items():
            problem = _hist_problem(d, require_samples=False)
            if problem:
                return fail(f"histogram {rep_key}/{name}: {problem}")
    p50 = detail.get("serving_lookup_p50_ms")
    p99 = detail.get("serving_lookup_p99_ms")
    if p50 is None or p99 is None or not p50 <= p99:
        return fail(f"serving lookup p50/p99 malformed ({p50}, {p99})")
    print(
        "check_bench_report: histograms ok "
        f"(serving lookup p50 {p50} ms / p99 {p99} ms, "
        f"{len(scan_hists)} scan-leg distributions)"
    )
    return 0


def check_report(bench_log: pathlib.Path) -> int:
    lines = [
        line for line in bench_log.read_text().splitlines()
        if line.startswith("{")
    ]
    if not lines:
        return fail(f"no JSON line in bench output {bench_log}")
    try:
        result = json.loads(lines[-1])
    except ValueError as e:
        return fail(f"bench JSON does not parse: {e}")
    rep = result.get("detail", {}).get("scan_report")
    if not isinstance(rep, dict):
        return fail("bench detail carries no scan_report")
    missing = [k for k in REPORT_KEYS if k not in rep]
    if missing:
        return fail(f"scan_report missing keys: {missing}")
    if not rep["bytes_read"] > 0:
        return fail("scan_report.bytes_read is not positive")
    if not rep["stages"]:
        return fail("scan_report.stages is empty")
    print(f"check_bench_report: scan_report ok ({len(rep['stages'])} stages, "
          f"{rep['bytes_read']} bytes read)")
    return (
        check_remote_leg(result.get("detail", {}))
        or check_serving_leg(result.get("detail", {}))
        or check_traffic_leg(result.get("detail", {}))
        or check_fleet_leg(result.get("detail", {}))
        or check_fleet_trace(result.get("detail", {}))
        or check_histograms(result.get("detail", {}))
        or check_exec_cache_leg(result.get("detail", {}))
        or check_multichip_leg(result.get("detail", {}))
        or check_launches(result.get("detail", {}))
        or check_loader_leg(result.get("detail", {}))
        or check_pushdown_leg(result.get("detail", {}))
        or check_write_leg(result.get("detail", {}))
        or check_compact_leg(result.get("detail", {}))
        or check_query_leg(result.get("detail", {}))
    )


def check_write_leg(detail: dict) -> int:
    """The device write path (docs/write.md): device-encode rows/s must
    hold >= 0.25x the decode leg's scan rate, the read-back must be
    value-exact, device columns must actually have ridden the fused
    launches (exactly analyze+pack per row group), and every group must
    have landed."""
    for key in ("write_rows_per_sec", "write_vs_scan_x", "write_groups",
                "write_launches", "write_device_columns", "write_exact"):
        if key not in detail:
            return fail(f"write leg missing {key}")
    if not detail["write_exact"]:
        return fail("write leg read-back is not value-exact")
    if detail["write_vs_scan_x"] < 0.25:
        return fail(
            f"device-encode rows/s floor broken: write_vs_scan_x="
            f"{detail['write_vs_scan_x']} < 0.25"
        )
    groups = detail["write_groups"]
    if groups < 1:
        return fail("write leg wrote no groups")
    if detail["write_launches"] != 2 * groups:
        return fail(
            f"write launch shape broken: {detail['write_launches']} "
            f"launches for {groups} groups (want analyze+pack = "
            f"{2 * groups})"
        )
    if detail["write_device_columns"] < 1:
        return fail("no column rode the device encode path")
    print(
        "check_bench_report: write leg ok "
        f"({detail['write_rows_per_sec']} rows/s, "
        f"{detail['write_vs_scan_x']}x scan, "
        f"{detail['write_device_columns']} device columns)"
    )
    return 0


def check_compact_leg(detail: dict) -> int:
    """The compaction service (docs/write.md): compaction must run at
    >= 0.5x the interleaved device-scan comparator over the same
    corpus, preserve every row value-exactly, and land output row
    groups exactly in the target band (== target, except each file's
    last group)."""
    for key in ("compact_vs_scan_x", "compact_rows_per_sec",
                "compact_group_rows", "compact_target_group_rows",
                "compact_files_out", "compact_exact"):
        if key not in detail:
            return fail(f"compact leg missing {key}")
    if not detail["compact_exact"]:
        return fail("compacted output is not value-exact vs its input")
    if detail["compact_vs_scan_x"] < 0.5:
        return fail(
            f"compaction speed floor broken: compact_vs_scan_x="
            f"{detail['compact_vs_scan_x']} < 0.5"
        )
    target = detail["compact_target_group_rows"]
    sizes = detail["compact_group_rows"]
    if not sizes:
        return fail("compact leg wrote no groups")
    # with one output file, every group but the last must be EXACTLY
    # the target; the last may be a short tail
    files = detail["compact_files_out"]
    if files == 1:
        bad = [s for s in sizes[:-1] if s != target]
        if bad or not 0 < sizes[-1] <= target:
            return fail(
                f"output group sizes {sizes} outside the target band "
                f"(target {target})"
            )
    else:
        if any(s > target for s in sizes):
            return fail(
                f"output group sizes {sizes} exceed target {target}"
            )
    print(
        "check_bench_report: compact leg ok "
        f"({detail['compact_rows_per_sec']} rows/s, "
        f"{detail['compact_vs_scan_x']}x scan, groups {sizes})"
    )
    return 0


def check_query_leg(detail: dict) -> int:
    """The query subsystem (docs/query.md): the sorted-merge join must
    hold >= 0.5x the two-scan lower bound over the same corpora, an
    indexed point probe on a NON-sort column must cost at most one
    data page of cold storage bytes (and an absent key exactly zero),
    and the fused expression projection must be BIT-equal to
    pyarrow.compute at <= 1 launch per row group."""
    for key in ("query_join_vs_twoscan_x", "query_join_out_rows",
                "query_join_pages", "query_index_probe_bytes",
                "query_index_absent_bytes", "query_index_page_bound",
                "query_index_hits", "query_expr_exact",
                "query_expr_groups", "query_expr_launches"):
        if key not in detail:
            return fail(f"query leg missing {key}")
    if detail["query_join_vs_twoscan_x"] < 0.5:
        return fail(
            f"join speed floor broken: query_join_vs_twoscan_x="
            f"{detail['query_join_vs_twoscan_x']} < 0.5"
        )
    if detail["query_join_out_rows"] < 1:
        return fail("join produced no rows")
    if detail["query_join_pages"] < 1:
        return fail("join counted no pages (query.join_pages)")
    if detail["query_index_hits"] < 1:
        return fail("indexed probe never hit the index rung")
    bound = detail["query_index_page_bound"]
    cost = detail["query_index_probe_bytes"]
    if not 0 < cost <= bound:
        return fail(
            f"indexed probe cost {cost} outside (0, one data page "
            f"{bound}]"
        )
    if detail["query_index_absent_bytes"] != 0:
        return fail(
            f"absent-key probe read {detail['query_index_absent_bytes']}"
            " bytes — the index must prove absence for free"
        )
    if not detail["query_expr_exact"]:
        return fail("expression projection is not bit-equal to "
                    "pyarrow.compute")
    groups = detail["query_expr_groups"]
    if groups < 1:
        return fail("expression scan decoded no groups")
    if detail["query_expr_launches"] > groups:
        return fail(
            f"expression launch shape broken: "
            f"{detail['query_expr_launches']} launches for {groups} "
            f"groups (want <= 1/group)"
        )
    print(
        "check_bench_report: query leg ok "
        f"({detail['query_join_vs_twoscan_x']}x two-scan, probe "
        f"{cost}B <= {bound}B, {detail['query_expr_launches']} "
        f"launches/{groups} groups)"
    )
    return 0


def check_exec_cache_leg(detail: dict) -> int:
    """The persistent-executable-cache leg (docs/perf.md): the cold
    subprocess must have compiled (misses >= 1) and the warm one must
    not (hits >= 1, zero compile wall), the warm first-group wall must
    be >= 10x better, and both runs' decoded digests bit-identical —
    the cache may only ever change WHEN compilation happens, never what
    decodes."""
    cold_wall = detail.get("exec_cache_cold_first_group_wall_ms")
    warm_wall = detail.get("exec_cache_warm_first_group_wall_ms")
    if not cold_wall or not warm_wall:
        return fail("exec-cache leg missing first-group walls")
    if not detail.get("exec_cache_cold_misses", 0) >= 1:
        return fail("exec-cache cold run resolved no executable (miss)")
    if not detail.get("exec_cache_cold_compile_ms", 0) > 0:
        return fail("exec-cache cold run recorded no compile wall")
    if not detail.get("exec_cache_warm_hits", 0) >= 1:
        return fail("exec-cache warm run hit nothing — the persisted "
                    "entry was not loaded")
    if detail.get("exec_cache_warm_misses", 0) != 0:
        return fail("exec-cache warm run recompiled "
                    f"({detail['exec_cache_warm_misses']} miss(es))")
    if detail.get("exec_cache_warm_compile_ms", 0) != 0:
        return fail("exec-cache warm run spent compile wall "
                    f"({detail['exec_cache_warm_compile_ms']} ms)")
    if detail.get("exec_cache_bit_identical") is not True:
        return fail("exec-cache warm decode is not bit-identical to cold")
    for k in ("exec_cache_cold_launches", "exec_cache_warm_launches"):
        if detail.get(k) != 1:
            return fail(f"{k} is {detail.get(k)!r}, expected exactly 1 "
                        "(one fused launch per in-cap row group)")
    speedup = cold_wall / warm_wall
    if not speedup >= 10.0:
        return fail(f"exec-cache warm start is only {speedup:.1f}x better "
                    f"than cold ({warm_wall} ms vs {cold_wall} ms) — "
                    "the persisted cache should eliminate the compile")
    print(
        "check_bench_report: exec-cache leg ok "
        f"(cold {cold_wall} ms -> warm {warm_wall} ms, {speedup:.1f}x; "
        f"cold compile {detail['exec_cache_cold_compile_ms']} ms)"
    )
    return 0


def check_multichip_leg(detail: dict) -> int:
    """The multi-chip scheduler leg (docs/multichip.md): delivery must
    be bit-identical across the serial / single-device / mesh passes,
    every group must have been mesh-placed and fused-dispatched exactly
    once, the inflate-overlap fraction must be >= 0.5 (the serial
    baseline shows what unoverlapped looks like), and on a real
    accelerator mesh (``multichip_gate_expected``) the mesh pass must
    deliver >= 0.7*k the single-chip throughput."""
    groups = detail.get("multichip_groups")
    if not groups or not groups > 0:
        return fail("multichip leg delivered no groups")
    if detail.get("multichip_bit_identical") is not True:
        return fail("multichip delivery is not bit-identical across the "
                    "serial / single / mesh passes")
    if detail.get("multichip_mesh_groups") != groups:
        return fail(f"multichip scheduler placed "
                    f"{detail.get('multichip_mesh_groups')!r} groups on "
                    f"the mesh, expected all {groups}")
    if detail.get("multichip_launches") != groups:
        return fail(f"multichip mesh pass dispatched "
                    f"{detail.get('multichip_launches')!r} launches for "
                    f"{groups} groups — the mesh moves launches, it "
                    "must never multiply them")
    if detail.get("multichip_events_dropped", 0) != 0:
        return fail("multichip mesh pass dropped timeline events — the "
                    "overlap fraction below is not trustworthy")
    overlap = detail.get("multichip_overlap_fraction")
    if overlap is None:
        return fail("multichip leg measured no inflate overlap (no "
                    "inflate span closed — wrong codec?)")
    if not overlap >= 0.5:
        return fail(f"multichip inflate overlap is {overlap:.2f} "
                    f"(serial baseline "
                    f"{detail.get('multichip_overlap_serial', 0):.2f}) — "
                    "host inflate must hide under pipeline work")
    k = detail.get("multichip_devices", 0)
    speedup = detail.get("multichip_speedup_x")
    if detail.get("multichip_gate_expected"):
        if speedup is None or not speedup >= 0.7 * k:
            return fail(f"multichip mesh speedup is {speedup!r}x on a "
                        f"{k}-device accelerator mesh, gate is "
                        f">= {0.7 * k:.1f}x")
    print(
        "check_bench_report: multichip leg ok "
        f"({groups} groups over {k} devices on "
        f"{detail.get('multichip_platform')}, overlap {overlap:.2f}, "
        f"speedup {speedup!r}x, gate "
        f"{'ENFORCED' if detail.get('multichip_gate_expected') else 'parity-only'})"
    )
    return 0


def check_launches(detail: dict) -> int:
    """The one-launch contract on the scan leg's counted pass: exactly
    one fused dispatch per delivered IN-CAP row group.  Groups past the
    arena cap legitimately take the multi-launch chunked fallback
    (docs/perf.md) — with any present, the strict equality relaxes to a
    floor."""
    groups = detail.get("scan_groups")
    launches = detail.get("scan_launches")
    overcap = detail.get("scan_overcap_groups", 0)
    if not groups or not groups > 0:
        return fail("scan leg delivered no groups")
    if overcap == 0 and launches != groups:
        return fail(f"scan leg dispatched {launches} launches for "
                    f"{groups} in-cap row groups — the fused path must "
                    "be exactly one launch per in-cap group")
    if overcap > 0 and not launches >= groups:
        return fail(f"scan leg dispatched {launches} launches for "
                    f"{groups} groups ({overcap} over-cap) — fewer "
                    "launches than groups is impossible")
    print(f"check_bench_report: one-launch ok ({launches} launches / "
          f"{groups} groups, {overcap} over-cap)")
    return 0


def check_pushdown_leg(detail: dict) -> int:
    """Device pushdown compute (docs/pushdown.md): the selective filter
    scan must ship ≤ 0.1x the ship-columns baseline's D2H bytes with
    results bit-identical to pyarrow.compute, the one-launch contract
    must hold WITH the compute tail fused (launches == groups + counted
    capacity overflows; the ~1% bench filter must see zero overflows),
    and the group-by aggregate must be bit-equal to pyarrow's
    group_by().aggregate with O(groups) D2H."""
    groups = detail.get("pushdown_groups")
    if not groups or not groups > 0:
        return fail("pushdown leg delivered no groups")
    launches = detail.get("pushdown_launches")
    overflows = detail.get("pushdown_overflows", 0)
    if overflows != 0:
        return fail(f"pushdown leg hit {overflows} capacity overflow(s) "
                    "on a ~1% filter — the initial-capacity policy "
                    "regressed")
    if launches != groups:
        return fail(f"pushdown leg dispatched {launches} launches for "
                    f"{groups} groups — the compute tail must fuse into "
                    "the ONE decode launch")
    if not detail.get("pushdown_filter_exact"):
        return fail("pushdown filter results are not bit-identical to "
                    "pyarrow.compute")
    if not detail.get("pushdown_agg_exact"):
        return fail("pushdown group-by aggregate is not bit-equal to "
                    "pyarrow group_by().aggregate")
    ratio = detail.get("pushdown_d2h_ratio")
    if ratio is None or ratio > 0.1:
        return fail(f"pushdown filter scan shipped {ratio}x the "
                    "ship-columns baseline's D2H bytes (must be <= 0.1x)")
    agg_bytes = detail.get("pushdown_agg_d2h_bytes", 0)
    base = detail.get("pushdown_baseline_d2h_bytes", 0)
    if not agg_bytes or agg_bytes > 0.1 * base:
        return fail(f"aggregate D2H {agg_bytes} B is not O(groups) "
                    f"(baseline {base} B)")
    print(
        "check_bench_report: pushdown leg ok "
        f"({detail.get('pushdown_rows_selected')}/"
        f"{detail.get('pushdown_rows_in')} rows shipped, "
        f"D2H {ratio}x baseline, {launches} launches / {groups} groups, "
        f"agg {detail.get('pushdown_agg_groups')} keys "
        f"{agg_bytes} B)"
    )
    return 0


def check_remote_leg(detail: dict) -> int:
    """The cold-storage truth bench (docs/remote.md): on the simulated
    20 ms-RTT store the scheduled scan's overlap_fraction must clear
    0.5 while the sequential per-file loop stays under 0.1 — the
    assertion docs/scan.md promised once real latency made the overlap
    visible.  The fault-heavy pass must be bit-identical to the clean
    one with hedge/retry/breaker/throttle counters all exercised, and
    every counter it emitted must be registered in ``trace.names``."""
    overlap = detail.get("remote_overlap_fraction")
    seq = detail.get("remote_seq_overlap_fraction")
    if overlap is None or seq is None:
        return fail("remote leg missing overlap fractions")
    if not overlap >= 0.5:
        return fail(f"remote scan overlap_fraction {overlap} < 0.5 on the "
                    f"{detail.get('remote_rtt_ms')} ms-RTT store")
    if not seq < 0.1:
        return fail(f"remote sequential overlap_fraction {seq} >= 0.1 — "
                    "the baseline should be I/O-bound")
    if detail.get("remote_seq_bit_identical") is not True:
        return fail("remote scheduled scan is not bit-identical to the "
                    "sequential loop")
    if detail.get("remote_fault_bit_identical") is not True:
        return fail("fault-heavy remote scan diverged from the clean run")
    for counter in ("remote_hedges", "remote_retries",
                    "remote_breaker_trips", "remote_throttles"):
        if not detail.get(counter, 0) >= 1:
            return fail(f"fault-heavy remote scan never exercised {counter}")
    fault_rep = detail.get("remote_fault_scan_report") or {}
    emitted = set(fault_rep.get("counters") or {})
    emitted |= set(fault_rep.get("gauges") or {})
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    from parquet_floor_tpu.utils.trace import names

    unregistered = emitted - names.ALL
    if unregistered:
        return fail(f"remote counters not in trace.names: "
                    f"{sorted(unregistered)}")
    print(
        "check_bench_report: remote leg ok "
        f"(overlap {overlap} vs sequential {seq}; "
        f"hedges={detail['remote_hedges']} retries={detail['remote_retries']} "
        f"breaker_trips={detail['remote_breaker_trips']} "
        f"throttles={detail['remote_throttles']})"
    )
    return 0


def check_serving_leg(detail: dict) -> int:
    """The multi-tenant serving leg (docs/serving.md): with two tenants
    scanning overlapping data through the shared buffer cache, the
    second tenant's pass must be served mostly from memory; concurrent
    tenants' reports must stay disjoint and correctly attributed; a hot
    one-column ``Dataset.lookup`` must cost at most ONE data page of
    storage bytes (and more than zero — a free probe means the page was
    pre-cached and the proof proves nothing); the pruning ladder's
    stats and bloom rungs must both fire; and every serve.* metric the
    leg emitted must be registered in ``trace.names``."""
    rate = detail.get("serving_hit_rate_second_pass")
    if rate is None:
        return fail("serving leg missing its second-pass hit rate")
    if not rate >= 0.5:
        return fail(f"serving second tenant's cache hit-rate {rate} < 0.5 "
                    "— the shared cache is not sharing")
    if not detail.get("serving_rows", 0) > 0 or \
            detail.get("serving_second_rows") != detail.get("serving_rows"):
        return fail("serving tenants disagree on the dataset's rows")
    if detail.get("serving_tenants_disjoint") is not True:
        return fail("concurrent tenants' reports are not disjoint / "
                    "correctly attributed")
    cost = detail.get("serving_lookup_storage_bytes")
    bound = detail.get("serving_lookup_page_bound")
    if cost is None or not bound:
        return fail("serving leg missing the lookup byte-cost proof")
    if not 0 < cost <= bound:
        return fail(f"hot one-column lookup read {cost} storage bytes "
                    f"(one-page bound {bound}) — the point probe must "
                    "touch one page, not a row group")
    if not detail.get("serving_lookup_groups_pruned", 0) >= 1:
        return fail("lookup never pruned a row group by footer stats")
    if not detail.get("serving_lookup_bloom_skips", 0) >= 1:
        return fail("lookup never skipped a row group by bloom filter")
    if detail.get("serving_remote_rows", 0) <= 0:
        return fail("serving remote tenants disagree (or read no rows)")
    rrate = detail.get("serving_remote_warm_hit_rate")
    if rrate is None or not rrate >= 0.5:
        return fail(f"serving remote warm hit-rate {rrate} < 0.5 — the "
                    "cache law does not hold over the remote source")
    rep = detail.get("serving_report") or {}
    emitted = set(rep.get("counters") or {}) | set(rep.get("gauges") or {})
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    from parquet_floor_tpu.utils.trace import names

    unregistered = emitted - names.ALL
    if unregistered:
        return fail(f"serving counters not in trace.names: "
                    f"{sorted(unregistered)}")
    print(
        "check_bench_report: serving leg ok "
        f"(second-pass hit-rate {rate}, lookup {cost} B <= {bound} B page "
        f"bound, bloom skips {detail['serving_lookup_bloom_skips']}, "
        f"remote warm hit-rate {rrate})"
    )
    return 0


def check_traffic_leg(detail: dict) -> int:
    """The process-scale traffic truth bench (docs/serving.md):

    * 4 worker processes over one shared ShmCacheTier must reach >=
      2.5x one worker's aggregate lookup throughput (latency-bound
      storage — the scaling a per-process cache can never show), with
      the cross-process single-flight path actually exercised;
    * the zipf open-loop pass must hold p99 (measured from SCHEDULED
      arrival, queueing included) within its recorded SLO target, with
      a well-formed latency histogram;
    * the cache-hot aggressor (3x the light tenant's offered load)
      must EXCEED its weight share of device time ungated and be held
      within the recorded band of the WFQ-ideal share by the 1-lane
      device gate — storage bytes it never touches cannot buy it the
      decode engine."""
    for key in ("traffic_worker1_rps", "traffic_workers_rps",
                "traffic_scaling_x", "traffic_workers",
                "traffic_p50_ms", "traffic_p99_ms", "traffic_slo_p99_ms",
                "traffic_slo_ok", "traffic_hist",
                "traffic_fair_share_hot", "traffic_fair_share_hot_ungated",
                "traffic_fairness_err", "traffic_fair_band",
                "traffic_shm_singleflight_waits",
                "traffic_fair_hot_hit_rate"):
        if key not in detail:
            return fail(f"traffic leg missing {key}")
    if detail["traffic_workers"] < 4:
        return fail(f"traffic leg ran {detail['traffic_workers']} workers, "
                    "expected >= 4")
    x = detail["traffic_scaling_x"]
    if not x >= 2.5:
        return fail(
            f"4-worker aggregate throughput only {x}x one worker "
            "(floor 2.5x) — the cross-process tier is not scaling"
        )
    if not detail["traffic_shm_singleflight_waits"] >= 1:
        return fail("the scaling pass never took a cross-process "
                    "single-flight wait — the shared tier went unexercised")
    p50, p99 = detail["traffic_p50_ms"], detail["traffic_p99_ms"]
    slo = detail["traffic_slo_p99_ms"]
    if not 0 < p50 <= p99:
        return fail(f"open-loop p50/p99 malformed ({p50}, {p99})")
    if not detail["traffic_slo_ok"] or not p99 <= slo:
        return fail(f"open-loop p99 {p99} ms violates the {slo} ms SLO "
                    "target under zipf Poisson load")
    problem = _hist_problem(detail["traffic_hist"])
    if problem:
        return fail(f"traffic latency histogram: {problem}")
    hot_hit = detail["traffic_fair_hot_hit_rate"]
    if not hot_hit >= 0.9:
        return fail(f"fairness aggressor's hit-rate {hot_hit} < 0.9 — "
                    "the pass needs a CACHE-HOT aggressor to prove "
                    "anything about device-time fairness")
    ungated = detail["traffic_fair_share_hot_ungated"]
    if not ungated >= 0.6:
        return fail(
            f"ungated aggressor share {ungated} < 0.6 — the comparator "
            "never exceeded its weight share, so the gated pass proves "
            "nothing"
        )
    err, band = detail["traffic_fairness_err"], detail["traffic_fair_band"]
    if not err <= band:
        return fail(
            f"device-time fairness error {err} exceeds the {band} band "
            f"(gated share {detail['traffic_fair_share_hot']} vs ideal "
            f"{detail.get('traffic_fair_ideal')}) — the cache-hot tenant "
            "still buys extra engine time"
        )
    print(
        "check_bench_report: traffic leg ok "
        f"(scaling {x}x at {detail['traffic_workers']} workers, "
        f"open-loop p99 {p99} ms <= {slo} ms SLO, "
        f"hot share {detail['traffic_fair_share_hot']} vs ungated "
        f"{ungated}, err {err} <= {band})"
    )
    return 0


def check_fleet_leg(detail: dict) -> int:
    """The fleet-survivability leg (docs/serving.md):

    * fleet-wide origin reads must stay ~exactly-once per unique range
      (<= the recorded 1.25x ceiling), with the peer-fetch leg and
      hot-range replication both actually exercised;
    * the host-loss chaos pass must answer EVERY request byte-correct
      with zero errors — a dead or fenced owner degrades to an origin
      fallback, never to a wrong answer or an exception;
    * the stale-epoch fence must have refused at least one asker, and
      the explicit stale probe must have come back ``stale_epoch``;
    * chaos-pass p99 (failover + fence window + reinstall included)
      must hold the recorded SLO, over a well-formed histogram."""
    for k in ("fleet_nodes", "fleet_unique_ranges", "fleet_origin_reads",
              "fleet_origin_ratio", "fleet_origin_ratio_max",
              "fleet_exactly_once_ok", "fleet_peer_hits",
              "fleet_replications", "fleet_peer_fallbacks",
              "fleet_fenced", "fleet_fence_refused", "fleet_wrong",
              "fleet_chaos_requests", "fleet_chaos_errors",
              "fleet_chaos_p99_ms", "fleet_chaos_slo_ms",
              "fleet_chaos_slo_ok", "fleet_chaos_hist"):
        if k not in detail:
            return fail(f"fleet leg missing {k}")
    ratio = detail["fleet_origin_ratio"]
    ceiling = detail["fleet_origin_ratio_max"]
    if not detail["fleet_exactly_once_ok"] or not ratio <= ceiling:
        return fail(
            f"fleet origin reads {detail['fleet_origin_reads']} for "
            f"{detail['fleet_unique_ranges']} unique ranges "
            f"({ratio}x > {ceiling}x) — the fabric is re-reading origin"
        )
    if not detail["fleet_peer_hits"] >= 1:
        return fail("fleet leg never took a peer hit — the peer leg "
                    "went unexercised")
    if not detail["fleet_replications"] >= 1:
        return fail("fleet leg never replicated a hot range")
    if not detail["fleet_peer_fallbacks"] >= 1:
        return fail("chaos pass never fell back to origin — the host "
                    "loss went unexercised")
    if detail["fleet_wrong"] != 0:
        return fail(f"fleet leg answered {detail['fleet_wrong']} "
                    "request(s) with WRONG bytes")
    if detail["fleet_chaos_errors"] != 0:
        return fail(f"chaos pass raised {detail['fleet_chaos_errors']} "
                    "error(s) — peer failure must degrade, not raise")
    if not detail["fleet_chaos_requests"] >= 1:
        return fail("chaos pass issued no requests")
    if not detail["fleet_fenced"] >= 1 or not detail["fleet_fence_refused"]:
        return fail("the stale-epoch fence never refused an asker")
    p99, slo = detail["fleet_chaos_p99_ms"], detail["fleet_chaos_slo_ms"]
    if not detail["fleet_chaos_slo_ok"] or not p99 <= slo:
        return fail(f"chaos-pass p99 {p99} ms violates the {slo} ms SLO "
                    "through the host loss")
    problem = _hist_problem(detail["fleet_chaos_hist"])
    if problem:
        return fail(f"fleet chaos histogram: {problem}")
    print(
        "check_bench_report: fleet leg ok "
        f"({detail['fleet_origin_reads']} origin reads / "
        f"{detail['fleet_unique_ranges']} ranges = {ratio}x, "
        f"peer hits {detail['fleet_peer_hits']}, "
        f"replications {detail['fleet_replications']}, "
        f"fenced {detail['fleet_fenced']}, "
        f"chaos p99 {p99} ms <= {slo} ms)"
    )
    return 0


def check_fleet_trace(detail: dict) -> int:
    """The flight-recorder truth check on the chaos pass
    (docs/observability.md): the breaker trips / epoch fences the
    host-loss pass provokes must have AUTO-produced at least one
    incident bundle, and its merged fleet timeline must hold at least
    one request whose spans cross two or more daemons, with every
    parent link resolving inside its trace and every per-host track's
    complete events balanced and time-ordered."""
    for k in ("fleet_flight_bundles", "fleet_trace_span_events",
              "fleet_trace_cross_traces", "fleet_trace_cross_max_nodes",
              "fleet_trace_parent_links_ok", "fleet_trace_monotonic_ok",
              "fleet_trace_balanced_ok", "fleet_trace_clock_offsets",
              "fleet_trace_ok"):
        if k not in detail:
            return fail(f"fleet trace missing {k}")
    if not detail["fleet_flight_bundles"] >= 1:
        return fail("chaos pass produced no incident bundle — breaker "
                    "trips / fences never fired the flight recorder")
    if not detail["fleet_trace_span_events"] >= 1:
        return fail("incident bundle's merged timeline holds no spans")
    if not detail["fleet_trace_cross_traces"] >= 1 or \
            not detail["fleet_trace_cross_max_nodes"] >= 2:
        return fail("no request in the incident bundle crossed two "
                    "daemons — the distributed chain went unrecorded")
    if not detail["fleet_trace_parent_links_ok"]:
        return fail("incident bundle has dangling parent links — a "
                    "hop's span never reached the merge")
    if not detail["fleet_trace_monotonic_ok"]:
        return fail("merged fleet timeline has a non-monotonic track "
                    "after clock-offset rebasing")
    if not detail["fleet_trace_balanced_ok"]:
        return fail("merged fleet timeline has an unbalanced event "
                    "(negative ts or dur)")
    if not detail["fleet_trace_ok"]:
        return fail("fleet trace verdict is not ok")
    print(
        "check_bench_report: fleet trace ok "
        f"({detail['fleet_flight_bundles']} bundle(s), "
        f"{detail['fleet_trace_cross_traces']} cross-daemon trace(s) "
        f"over up to {detail['fleet_trace_cross_max_nodes']} nodes, "
        f"{detail['fleet_trace_span_events']} spans, offsets "
        f"{detail['fleet_trace_clock_offsets']})"
    )
    return 0


def check_loader_leg(detail: dict) -> int:
    """The training-loader leg (docs/data.md): throughput reported, at
    least one batch emitted, and the shuffled stream's key multiset
    bit-identical to the unshuffled reference (the exactness bit is
    deterministic — a False here is a real loader bug, not noise)."""
    if not detail.get("loader_rows_per_sec", 0) > 0:
        return fail("loader_rows_per_sec missing or not positive")
    if not detail.get("loader_batches", 0) > 0:
        return fail("loader leg emitted no batches")
    if detail.get("loader_set_exact") is not True:
        return fail("shuffled loader stream is not set-exact vs unshuffled")
    ratio = detail.get("loader_prefetch_vs_scan_x")
    if ratio is None or not ratio >= 1.0:
        return fail(f"double-buffered loader leg at {ratio}x raw scan "
                    "throughput — prefetch_to_device must clear 1.0x "
                    "(docs/perf.md)")
    print(
        "check_bench_report: loader leg ok "
        f"({detail['loader_batches']} batches, "
        f"{detail['loader_rows_per_sec']} rows/s, "
        f"vs scan x{detail.get('loader_vs_scan_x')}, "
        f"prefetch x{ratio})"
    )
    return 0


def check_chrome_trace(trace_path: pathlib.Path) -> int:
    try:
        data = json.loads(trace_path.read_text())
    except (OSError, ValueError) as e:
        return fail(f"chrome trace does not parse: {e}")
    events = data.get("traceEvents")
    if not events:
        return fail("chrome trace has no traceEvents")
    stacks = {}
    seen = set()
    last_ts = None
    for ev in events:
        if ev["ph"] == "M":
            continue
        if last_ts is not None and ev["ts"] < last_ts:
            return fail("chrome trace timestamps are not monotonic")
        last_ts = ev["ts"]
        if ev["ph"] == "B":
            stacks.setdefault(ev["tid"], []).append(ev["name"])
            seen.add(ev["name"])
        elif ev["ph"] == "E":
            stack = stacks.get(ev["tid"])
            if not stack:
                return fail(f"unbalanced E event on tid {ev['tid']}")
            stack.pop()
    open_spans = {t: s for t, s in stacks.items() if s}
    if open_spans:
        return fail(f"unclosed spans at end of trace: {open_spans}")
    if not SPAN_NAMES <= seen:
        return fail(f"trace misses pipeline spans: {sorted(SPAN_NAMES - seen)}")
    print(f"check_bench_report: chrome trace ok ({len(events)} events)")
    return 0


def main(argv) -> int:
    if len(argv) != 3:
        return fail("usage: check_bench_report.py BENCH_LOG CHROME_TRACE")
    rc = check_report(pathlib.Path(argv[1]))
    return rc or check_chrome_trace(pathlib.Path(argv[2]))


if __name__ == "__main__":
    sys.exit(main(sys.argv))
