#!/usr/bin/env python
"""Commit-gate fleet-cache smoke (docs/serving.md).

The cross-host laws, proven over real sockets — three in-process
``ServeDaemon``\\ s, each mounting a :class:`FleetCache` over one
COUNTED origin:

1. **fleet-wide exactly-once**: every node reads every unique range
   through its fleet tier; across the whole fabric each unique range
   must have been read from origin EXACTLY once (non-primaries
   peer-fetch the owner), with the peer leg actually exercised;
2. **host loss degrades, never errors**: one daemon dies and the OLD
   membership stays installed — a full re-read from the survivors must
   answer every range byte-correct (dead-owner fetches fall back to
   origin); an explicit stale-epoch probe must be FENCED; after the
   epoch-bumped reinstall the fabric must serve correctly again;
3. **token-bucket admission**: a daemon built with a
   :class:`TenantRateLimiter` must reject an over-rate tenant with
   ``rate_limited`` + ``retry_after_ms`` (never queue it), admit
   within-burst requests, and keep the connection usable after;
4. **fleet-wide metrics fold**: every daemon pushes its snapshot into
   one shared ``metrics_dir``; the ``merge_snapshot_dir`` fold must
   carry the fabric's fleet counters from ALL daemons.

Exit 0 on success, 1 with a diagnostic otherwise.
"""

from __future__ import annotations

import pathlib
import sys
import tempfile
import threading
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from parquet_floor_tpu.serve import (  # noqa: E402
    DaemonClient,
    FleetCache,
    FleetMembership,
    PeerClient,
    ServeDaemon,
    Serving,
    TenantRateLimiter,
)
from parquet_floor_tpu.utils import trace  # noqa: E402

NODES = ["n0", "n1", "n2"]
RANGES = [(i * 4096, 768) for i in range(24)]
KEY = ("fleet-smoke", 1 << 20)


def fail(msg: str) -> int:
    print(f"fleet_smoke: FAIL {msg}", file=sys.stderr)
    return 1


def content(offset: int, length: int) -> bytes:
    pat = f"smoke:{offset}:{length}:".encode("ascii")
    return (pat * (length // len(pat) + 1))[:length]


def main() -> int:
    origin_lock = threading.Lock()
    origin_counts: dict = {}

    def origin_read(key, ranges):
        with origin_lock:
            for (o, n) in ranges:
                origin_counts[(o, n)] = origin_counts.get((o, n), 0) + 1
        time.sleep(0.002)
        return [content(o, n) for (o, n) in ranges]

    membership = FleetMembership.create(NODES)
    tracer = trace.Tracer(enabled=True)
    with tempfile.TemporaryDirectory() as metrics_dir:
        servings, fleets, daemons = [], [], []
        try:
            for nid in NODES:
                srv = Serving(prefetch_bytes=4 << 20)
                fc = FleetCache(
                    nid, membership, origin=origin_read,
                    peer_timeout_s=1.0, breaker_threshold=2,
                    breaker_cooldown_s=0.2,
                )
                d = ServeDaemon(
                    srv, {}, fleet=fc, max_inflight=4, max_pending=32,
                    metrics_dir=metrics_dir, drain_timeout_s=2.0,
                    rate_limiter=TenantRateLimiter(
                        rate_per_s=2.0, burst=2.0),
                )
                d.start()
                servings.append(srv)
                fleets.append(fc)
                daemons.append(d)
            peers = {nid: ("127.0.0.1", d.port)
                     for nid, d in zip(NODES, daemons)}
            for fc in fleets:
                fc.install_membership(membership, peers)

            # -- law 1: fleet-wide exactly-once -------------------------
            for fc in fleets:
                with trace.using(tracer):
                    got = fc.read_through(
                        KEY, RANGES, lambda rs: origin_read(KEY, rs))
                for (o, n), data in zip(RANGES, got):
                    if data != content(o, n):
                        return fail(f"wrong bytes for range {(o, n)}")
            with origin_lock:
                over = {r: c for r, c in origin_counts.items() if c != 1}
                total = sum(origin_counts.values())
            if over:
                return fail(
                    f"origin reads not exactly-once: {over} "
                    f"({total} reads for {len(RANGES)} ranges)")
            hits = tracer.counters().get("serve.fleet_peer_hits", 0)
            if hits < 1:
                return fail("peer leg unexercised (no peer hits)")
            print(f"fleet_smoke: exactly-once ok ({total} origin reads "
                  f"for {len(RANGES)} ranges, {hits} peer hits)")

            # -- law 2: host loss degrades, never errors ----------------
            daemons[2].close()
            fleets[2].close()
            for fc in fleets[:2]:
                with trace.using(tracer):
                    got = fc.read_through(
                        KEY, RANGES, lambda rs: origin_read(KEY, rs))
                for (o, n), data in zip(RANGES, got):
                    if data != content(o, n):
                        return fail(
                            f"wrong bytes after host loss for {(o, n)}")
            with PeerClient("127.0.0.1", daemons[0].port) as probe:
                reply = probe.fetch(KEY, RANGES[0][0], RANGES[0][1],
                                    epoch=999)
            if reply.get("ok") or reply.get("code") != "stale_epoch":
                return fail(f"stale-epoch probe not fenced: {reply}")
            survivors = membership.without("n2")
            new_peers = {nid: peers[nid] for nid in survivors.members}
            for fc in fleets[:2]:
                fc.install_membership(survivors, new_peers)
            fresh = [(1 << 22) + o for (o, _) in RANGES[:8]]
            for fc in fleets[:2]:
                with trace.using(tracer):
                    got = fc.read_through(
                        KEY, [(o, 768) for o in fresh],
                        lambda rs: origin_read(KEY, rs))
                for o, data in zip(fresh, got):
                    if data != content(o, 768):
                        return fail(f"wrong bytes after reinstall at {o}")
            print(f"fleet_smoke: host-loss ok (epoch "
                  f"{fleets[0].epoch}, fence refused)")

            # -- law 3: token-bucket admission --------------------------
            with DaemonClient("127.0.0.1", daemons[0].port,
                              tenant="greedy") as client:
                codes: dict = {}
                retry_ms = 0
                for _ in range(6):
                    r = client.request("lookup", dataset="none", key=1)
                    codes[r.get("code")] = codes.get(r.get("code"), 0) + 1
                    if r.get("code") == "rate_limited":
                        retry_ms = max(retry_ms,
                                       r.get("retry_after_ms", 0))
                if codes.get("rate_limited", 0) < 1:
                    return fail(f"over-rate tenant never rejected: {codes}")
                if codes.get("bad_request", 0) < 1:
                    return fail(
                        f"within-burst requests not admitted: {codes}")
                if retry_ms < 1:
                    return fail("rate_limited reply carries no "
                                "retry_after_ms")
                if not client.ping():
                    return fail("connection unusable after rate_limited")
            print(f"fleet_smoke: admission ok ({codes}, "
                  f"retry_after {retry_ms} ms)")

            # -- law 4: fleet-wide metrics fold -------------------------
            # one snapshot per daemon, explicitly named (the closed
            # chaos victim's tracer still folds), through the real
            # directory fold
            from parquet_floor_tpu.utils.metrics_export import (
                merge_snapshot_dir,
                write_snapshot,
            )
            for i, d in enumerate(daemons):
                write_snapshot(
                    d.worker_snapshot(),
                    str(pathlib.Path(metrics_dir) / f"daemon-{i}.json"))
            merged = merge_snapshot_dir(metrics_dir)
            counters = merged.get("counters", {})
            if counters.get("serve.fleet_origin_reads", 0) < 1:
                return fail(
                    "fold carries no fleet origin reads: "
                    f"{sorted(k for k in counters if 'fleet' in k)}")
            if counters.get("serve.ratelimit_rejected", 0) < 1:
                return fail("fold carries no rate-limit rejections")
            print("fleet_smoke: metrics fold ok "
                  f"(origin_reads={counters['serve.fleet_origin_reads']}, "
                  f"ratelimit_rejected="
                  f"{counters['serve.ratelimit_rejected']})")
            print("fleet_smoke: PASS")
            return 0
        finally:
            for d in daemons:
                d.close()
            for fc in fleets:
                fc.close()
            for srv in servings:
                srv.close()


if __name__ == "__main__":
    sys.exit(main())
