#!/usr/bin/env python
"""Self-contained lint gate — the checkstyle analogue the reference runs
in CI (/root/reference/pipeline.yml:33-63, checkstyle.xml:8-16).

Prefers ruff when installed (config in pyproject.toml).  Otherwise runs a
built-in subset that needs only the standard library, so the gate works
in hermetic images: syntax (compile), tabs, trailing whitespace, long
lines, and AST-level unused-import detection.

Either way it then runs **floorlint** (``python -m parquet_floor_tpu.analysis``)
— the project-invariant analyzer (error-taxonomy / tracer-purity /
resource / allocation rules; docs/static_analysis.md).  Style and
invariants are one gate: ``python scripts/lint.py`` fails if either does.
"""

from __future__ import annotations

import ast
import os
import pathlib
import shutil
import subprocess
import sys
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent
TARGETS = ["parquet_floor_tpu", "tests", "benchmarks", "scripts",
           "bench.py", "__graft_entry__.py"]
FLOORLINT_TARGETS = ["parquet_floor_tpu", "tests", "scripts"]
MAX_LINE = 100
# wall-clock ceiling for the floorlint project pass (override:
# PFTPU_FLOORLINT_BUDGET_S).  The whole-package symbol-table + call
# graph build is linear by construction; this gate catches a quadratic
# regression (an uncached per-rule re-walk, an unbounded traversal)
# before it rots the commit loop.  ~5 s on the dev container today.
FLOORLINT_BUDGET_S = float(os.environ.get("PFTPU_FLOORLINT_BUDGET_S", "30"))
# the warm incremental run must be a cache hit: a stat walk plus one
# unpickle.  5 s is ~20x headroom on the dev container (~0.3 s today).
FLOORLINT_WARM_BUDGET_S = float(os.environ.get("PFTPU_FLOORLINT_WARM_S", "5"))


def python_files():
    for t in TARGETS:
        p = ROOT / t
        if p.is_file():
            yield p
        else:
            yield from sorted(p.rglob("*.py"))


def run_ruff() -> int:
    return subprocess.call(
        ["ruff", "check", *TARGETS], cwd=ROOT
    )


def _dunder_all(tree: ast.AST) -> set:
    """Names re-exported via ``__all__`` (plain or augmented assignment of
    string-literal lists/tuples) — parsed from the AST, not by grepping the
    source for quoted strings (which also matched docstrings and error
    messages, hiding genuinely dead imports)."""
    names = set()
    for node in ast.walk(tree):
        value = None
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "__all__"
            for t in node.targets
        ):
            value = node.value
        elif isinstance(node, ast.AugAssign) and isinstance(
            node.target, ast.Name
        ) and node.target.id == "__all__":
            value = node.value
        if isinstance(value, (ast.List, ast.Tuple)):
            names |= {
                e.value for e in value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            }
    return names


def _unused_imports(tree: ast.AST, src: str):
    """Module-level imports never referenced anywhere in the file."""
    imported = {}  # name -> lineno
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                imported[name] = node.lineno
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                imported[a.asname or a.name] = node.lineno
    used = {
        n.id for n in ast.walk(tree) if isinstance(n, ast.Name)
    } | {
        n.attr for n in ast.walk(tree) if isinstance(n, ast.Attribute)
    } | _dunder_all(tree)
    # "# noqa" on the import line suppresses, as ruff would
    src_lines = src.splitlines()
    for name, lineno in sorted(imported.items()):
        if "# noqa" in src_lines[lineno - 1]:
            continue
        if name not in used:
            yield lineno, f"unused import: {name}"


def run_builtin() -> int:
    problems = []
    for path in python_files():
        rel = path.relative_to(ROOT)
        src = path.read_text()
        try:
            tree = ast.parse(src, filename=str(rel))
        except SyntaxError as e:
            problems.append(f"{rel}:{e.lineno}: syntax error: {e.msg}")
            continue
        for i, line in enumerate(src.splitlines(), 1):
            if "\t" in line:
                problems.append(f"{rel}:{i}: tab character")
            if line != line.rstrip():
                problems.append(f"{rel}:{i}: trailing whitespace")
            if len(line) > MAX_LINE and "http" not in line:
                problems.append(f"{rel}:{i}: line too long ({len(line)} > {MAX_LINE})")
        for lineno, msg in _unused_imports(tree, src):
            problems.append(f"{rel}:{lineno}: {msg}")
    for p in problems:
        print(p)
    print(f"lint: {len(problems)} problem(s) in {sum(1 for _ in python_files())} files")
    return 1 if problems else 0


def _family(rule: str) -> str:
    return rule.rstrip("0123456789")


def run_floorlint() -> int:
    """The invariant analyzer rides the same gate (its own CLI for use in
    editors: ``python -m parquet_floor_tpu.analysis --list-rules``).

    Runs in-process, TWICE against the ``.floorlint_cache/`` incremental
    cache: the first pass re-analyzes whatever changed (cold = everything
    on a fresh checkout), the second must be a run-tier cache hit.  Both
    walls print; the first is gated by ``PFTPU_FLOORLINT_BUDGET_S``, the
    warm one by the 5 s incremental ceiling (``PFTPU_FLOORLINT_WARM_S``)
    — findings, per-family counts, and runtime are all part of the
    contract."""
    sys.path.insert(0, str(ROOT))
    from parquet_floor_tpu.analysis import ALL_RULES, load_baseline
    from parquet_floor_tpu.analysis import run as floorlint_run
    from parquet_floor_tpu.analysis.cache import LintCache

    targets = [str(ROOT / t) for t in FLOORLINT_TARGETS]
    baseline = load_baseline(ROOT / "floorlint.baseline")
    cache = LintCache(ROOT / ".floorlint_cache")

    t0 = time.perf_counter()
    result = floorlint_run(targets, baseline=baseline, cache=cache)
    first_wall = time.perf_counter() - t0
    t1 = time.perf_counter()
    warm = floorlint_run(targets, baseline=baseline, cache=cache)
    warm_wall = time.perf_counter() - t1

    for v in result.violations:
        print(v.render())
    found = {}
    for v in result.violations:
        found[_family(v.rule)] = found.get(_family(v.rule), 0) + 1
    supp = {}
    for rule in result.suppressed_rules:
        supp[_family(rule)] = supp.get(_family(rule), 0) + 1
    families = sorted({_family(rule) for rule, _ in ALL_RULES})
    print("floorlint families: " + "  ".join(
        f"{fam}={found.get(fam, 0)}"
        + (f"(+{supp[fam]} suppressed)" if fam in supp else "")
        for fam in families))
    label = "cached" if result.from_cache else "analyzed"
    print(f"floorlint: {len(result.violations)} problem(s) in "
          f"{result.files} file(s); first run {first_wall:.2f}s "
          f"({label}, budget {FLOORLINT_BUDGET_S:.0f}s), warm run "
          f"{warm_wall:.2f}s (budget {FLOORLINT_WARM_BUDGET_S:.0f}s)")
    if first_wall > FLOORLINT_BUDGET_S:
        print("floorlint EXCEEDED its time budget — the project pass has "
              "regressed (uncached re-walk? unbounded traversal?); "
              "profile before raising PFTPU_FLOORLINT_BUDGET_S")
        return 1
    if not warm.from_cache or warm_wall > FLOORLINT_WARM_BUDGET_S:
        print("floorlint warm run was not an incremental cache hit within "
              f"{FLOORLINT_WARM_BUDGET_S:.0f}s — the cache keying has "
              "regressed (unstable signature? artifact store failing?)")
        return 1
    return 0 if result.ok else 1


if __name__ == "__main__":
    style_rc = run_ruff() if shutil.which("ruff") else run_builtin()
    floorlint_rc = run_floorlint()
    sys.exit(1 if (style_rc or floorlint_rc) else 0)
