#!/usr/bin/env bash
# The whole commit gate in one entry point:
#   1. style lint + floorlint (scripts/lint.py runs both; floorlint's
#      project pass — FL-RACE/FL-ASYNC concurrency rules included —
#      runs twice against .floorlint_cache/, prints per-family counts
#      plus first/warm wall times, and FAILS over its budgets:
#      PFTPU_FLOORLINT_BUDGET_S (default 30 s) for the analyzing run,
#      PFTPU_FLOORLINT_WARM_S (default 5 s) for the warm incremental
#      run — so a quadratic regression in the call-graph engine OR a
#      broken cache keying breaks this gate, not the commit loop's
#      patience)
#   2. tier-1 pytest (the ROADMAP.md verify recipe)
# Usage: scripts/check.sh [extra pytest args]
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== lint + floorlint (timed) =="
python scripts/lint.py || exit 1

echo "== tier-1 pytest =="
t1_log="$(mktemp /tmp/_t1.XXXXXX.log)"
trap 'rm -f "$t1_log"' EXIT
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
  -p no:randomly "$@" 2>&1 | tee "$t1_log"
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$t1_log" | tr -cd . | wc -c)"
[ "$rc" -eq 0 ] || exit "$rc"

# Fast bench smoke: every leg of bench.py (headline decode, batch face,
# chunked, multi-file scan, exec-cache cold/warm, device write,
# compaction) runs at toy scale on the CPU backend, so a broken decode
# OR encode path fails THIS gate instead of only the nightly bench.
# check_bench_report gates the write leg (device-encode rows/s >= 0.25x
# the decode leg, value-exact read-back, the analyze+pack launch shape)
# and the compact leg (>= 0.5x an interleaved scan comparator, output
# group sizes exactly in the target band) — docs/write.md.  The numbers are health indicators, not perf
# records.  Tracing is ON (PFTPU_TRACE=1) and the scan leg exports its
# ScanReport + Chrome trace, which check_bench_report.py then validates
# — a broken observability export fails the gate too
# (docs/observability.md).  The bench itself runs with a fresh
# PFTPU_EXEC_CACHE dir, so EVERY fused decode in the smoke rides the
# persistent-executable-cache dispatch path (its bit-exact checks then
# cover it); the exec-cache leg additionally runs one COLD and one WARM
# subprocess against one shared cache dir and check_bench_report
# asserts the >=10x warm-start shape (docs/perf.md).
echo "== bench smoke (PFTPU_BENCH_ROWS=2000, PFTPU_TRACE=1, exec cache on) =="
bench_log="$(mktemp /tmp/_bench.XXXXXX.log)"
bench_trace="$(mktemp /tmp/_btrace.XXXXXX.json)"
bench_cache="$(mktemp -d /tmp/_bcache.XXXXXX)"
trap 'rm -rf "$t1_log" "$bench_log" "$bench_trace" "$bench_cache"' EXIT
timeout -k 10 600 env JAX_PLATFORMS=cpu PFTPU_TRACE=1 PFTPU_BENCH_ROWS=2000 \
  PFTPU_BENCH_REPS=1 PFTPU_TRACE_EXPORT="$bench_trace" \
  PFTPU_EXEC_CACHE="$bench_cache" python bench.py \
  | tee "$bench_log"
[ "${PIPESTATUS[0]}" -eq 0 ] || exit 1
python scripts/check_bench_report.py "$bench_log" "$bench_trace" || exit 1

# Remote-scan smoke (docs/remote.md): the seeded latency/fault
# simulator at a 20 ms RTT — asserts the scheduled scan actually
# overlaps (overlap_fraction floor), then a fault-heavy pass (outage +
# heavy tail + throttling + seeded drops) completes bit-identical with
# retry/hedge/breaker counters all exercised and registered.
echo "== remote scan smoke (simulator, faults on) =="
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/remote_scan_smoke.py || exit 1

# Serving smoke (docs/serving.md, docs/observability.md): one cold
# tenant populates the shared buffer cache, two concurrent warm tenants
# must then be served from it (hit-rate floor per tenant, reports
# disjoint and attributed), and a hot one-column Dataset.lookup must
# cost at most ONE data page of storage bytes — the point-probe
# contract, proven by cache counters.  The telemetry floors ride the
# same gate: trace.serve_metrics on an ephemeral port is scraped
# MID-RUN and the body must validate as Prometheus text exposition with
# counter values matching cache.stats()/tracer truth; an injected slow
# tenant must trip serve.slo_breach from its per-tenant p99 histogram
# while a healthy tenant stays clean; and one trace.unified_trace
# export around a device scan must load as balanced/monotonic
# trace-event JSON whose XLA-capture events and host ship/decode spans
# overlap on ONE rebased clock.
echo "== serving smoke (shared cache, lookups, metrics, SLO, unified trace) =="
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/serving_smoke.py || exit 1

# Multi-PROCESS serving smoke (docs/serving.md): k=3 worker processes
# over one shared ShmCacheTier segment probing the same keys
# CONCURRENTLY (file-barrier start, modeled storage latency so reads
# really overlap) — every unique storage range read exactly ONCE
# across all workers (cross-process single-flight, with >= 1 real
# cross-process wait), a warm 4th worker served with ZERO storage
# reads, per-worker metrics snapshots disjoint and folding exactly
# through merge_snapshot_dir (file + HTTP aggregator), and the
# ServeDaemon contract: per-connection tenant attribution, stateless
# cursor paging, the metrics fold op, graceful drain.
echo "== process serving smoke (shm tier, workers, daemon, drain) =="
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/process_serving_smoke.py || exit 1

# Fleet-cache smoke (docs/serving.md): three in-process daemons each
# mounting a FleetCache over one COUNTED origin — fleet-wide
# exactly-once origin reads (non-primaries peer-fetch the owner), a
# host loss that degrades to origin fallback with every answer still
# byte-correct, a stale-epoch asker fenced, token-bucket admission
# rejecting an over-rate tenant with retry_after_ms before it queues,
# and the fleet-wide metrics fold carrying every daemon's counters.
echo "== fleet smoke (ownership, host loss, fencing, admission, fold) =="
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/fleet_smoke.py || exit 1

# Distributed-tracing smoke (docs/observability.md): three in-process
# daemons, every request under an ambient trace — the TraceContext must
# cross the fleet wire (peer hops land spans carrying the asker's
# trace_id, the DaemonClient front door yields a correct parent link +
# tenant), the per-daemon flight rings must merge into ONE balanced,
# per-track-monotonic Perfetto timeline with a cross-host parent edge,
# and one flight_fire must dump a verifiable incident bundle.
echo "== fleet trace smoke (context propagation, timeline merge, flight dump) =="
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/fleet_trace_smoke.py || exit 1

# Multi-chip mesh smoke (docs/multichip.md): a forced 4-device CPU
# mesh scan must deliver bit-identically to the single-device pass,
# place every group (engine.mesh_groups == groups == engine.launches),
# and spread them round-robin across all 4 devices (per-device floor).
echo "== multi-chip mesh smoke (forced 4 CPU devices) =="
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=4" \
  python scripts/mesh_smoke.py || exit 1

# Salvage differential smoke: 60 seeded corruption cases through ALL
# FOUR read faces (sequential host, host scan, device scan, loader),
# asserting unanimous fatality, identical quarantine sets, identical
# surviving bytes, and no silent divergence vs the clean decode
# (docs/robustness.md).  Fixed seeds, SIGALRM per case — a hang fails
# one case, not the gate's timeout.  The >=300-case sweep is the slow
# marker in tests/test_salvage_differential.py.
echo "== salvage differential smoke (60 cases, 4 faces) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu \
  python scripts/salvage_differential_smoke.py 60 30 || exit 1
exit 0
