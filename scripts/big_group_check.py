#!/usr/bin/env python
"""Prove the oversized-group chunk path on real hardware: write a single
row group whose decompressed bytes exceed the 2 GiB per-launch ceiling,
decode it through the TPU engine (which must split it into multiple
page-aligned launches), and verify the result by device-side checksum
(the tunnelled D2H link is too slow to fetch 2.4 GB back).

Run on the chip:  python scripts/big_group_check.py [--rows 300000000]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/pftpu_jax_cache")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=300_000_000)  # 2.4 GB of int64
    ap.add_argument("--path", default="/tmp/pftpu_big_group.parquet")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_enable_x64", True)

    import jax.numpy as jnp
    import numpy as np

    from parquet_floor_tpu import (
        CompressionCodec,
        ParquetFileWriter,
        WriterOptions,
        types,
    )
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader

    n = args.rows
    nbytes = n * 8
    print(f"backend: {jax.devices()[0].platform}; one row group of "
          f"{n:,} INT64 = {nbytes / 1e9:.2f} GB decompressed", flush=True)

    if not os.path.exists(args.path):
        schema = types.message("t", types.required(types.INT64).named("v"))
        opts = WriterOptions(
            codec=CompressionCodec.UNCOMPRESSED, enable_dictionary=False,
            page_version=2, data_page_values=4_000_000,
        )
        t0 = time.perf_counter()
        with ParquetFileWriter(args.path, schema, opts) as w:
            w.write_columns({"v": np.arange(n, dtype=np.int64)})
        print(f"wrote {os.path.getsize(args.path) / 1e9:.2f} GB in "
              f"{time.perf_counter() - t0:.1f}s", flush=True)

    with TpuRowGroupReader(args.path) as tr:
        est = tr._group_byte_estimate(tr.reader.row_groups[0])
        assert est > tr._arena_cap, (
            f"group estimate {est} does not exceed the cap {tr._arena_cap}"
        )
        print(f"group estimate {est / 1e9:.2f} GB > cap "
              f"{tr._arena_cap / 1e9:.2f} GB -> chunked decode", flush=True)
        t0 = time.perf_counter()
        g = tr.read_row_group(0)
        dc = g["v"]
        dev_sum = int(jnp.sum(dc.values))
        dev_n = int(dc.values.shape[0])
        dt = time.perf_counter() - t0
    exp_sum = n * (n - 1) // 2
    print(f"decoded {dev_n:,} rows in {dt:.1f}s "
          f"({nbytes / dt / 1e9:.2f} GB/s end-to-end)", flush=True)
    assert dev_n == n, (dev_n, n)
    assert dev_sum == exp_sum, (dev_sum, exp_sum)
    print("device checksum matches: OK", flush=True)


if __name__ == "__main__":
    main()
