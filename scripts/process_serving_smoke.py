#!/usr/bin/env python
"""Commit-gate MULTI-PROCESS serving smoke (docs/serving.md).

The cross-process laws, proven with real OS processes — k=3 worker
processes, one shared ``ShmCacheTier`` segment, one keyed dataset:

1. **cross-process single-flight**: the workers probe the SAME key list
   concurrently (file-barrier start); every real storage read is
   recorded inside each worker, and across ALL workers each unique
   ``(file, offset, length)`` range must have been read from storage
   EXACTLY once — the single-flight law crossing the process boundary;
2. **warm-worker hit-rate floor**: a fourth worker started after the
   segment is warm must complete every probe with ZERO storage reads
   (hit-rate 1.0 — stronger than any floor);
3. **per-tenant report disjointness across processes**: each worker
   runs under its own tenant scope and pushes a metrics snapshot; each
   snapshot must carry exactly ITS probe count, and the
   ``merge_snapshot_dir`` fold (also scraped over HTTP through
   ``MetricsServer(snapshot_dir=...)``) must equal the sum;
4. **daemon contract**: a ``ServeDaemon`` over the same files answers
   two tenant connections, attributes their probes to the right tenant
   tracers, folds the worker snapshots into its ``metrics`` op, and
   drains clean.

Exit 0 on success, 1 with a diagnostic otherwise.
"""

from __future__ import annotations

import json
import os
import pathlib
import shutil
import subprocess
import sys
import tempfile
import urllib.request

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

from parquet_floor_tpu import (  # noqa: E402
    ParquetFileWriter,
    WriterOptions,
    types,
)
from parquet_floor_tpu.serve import (  # noqa: E402
    DaemonClient,
    Dataset,
    ServeDaemon,
    Serving,
    ShmCacheTier,
)

GROUP = 256
PAGE = 64
GROUPS = 4
FILES = 2
WORKERS = 3
WORKER_SCRIPT = str(
    pathlib.Path(__file__).resolve().parent / "serve_worker.py"
)


def fail(msg: str) -> int:
    print(f"process_serving_smoke: FAIL — {msg}", file=sys.stderr)
    return 1


def build_paths() -> list:
    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.required(types.DOUBLE).named("d"),
    )
    per = GROUP * GROUPS
    paths = []
    for i in range(FILES):
        p = f"/tmp/pftpu_proc_smoke_{per}_{i}.parquet"
        if not os.path.exists(p):
            rng = np.random.default_rng(70 + i)
            with ParquetFileWriter(p, schema, WriterOptions(
                row_group_rows=GROUP, data_page_values=PAGE,
                bloom_filter_columns={"k": True},
            )) as w:
                for lo in range(0, per, GROUP):
                    base = 2 * (i * per + lo)
                    w.write_columns({
                        "k": base + 2 * np.arange(GROUP, dtype=np.int64),
                        "s": [None if j % 9 == 0 else f"s{j % 41}"
                              for j in range(GROUP)],
                        "d": rng.standard_normal(GROUP),
                    })
        paths.append(p)
    return paths


def run_workers(tier: ShmCacheTier, paths: list, keys: list,
                names: list, metrics_dir: str, tmp: str,
                concurrent: bool) -> list:
    """Spawn one worker process per name, release the start barrier
    once all are ready, and return their parsed result JSONs."""
    go = os.path.join(tmp, f"go-{'-'.join(names)}")
    procs = []
    for name in names:
        cfg = {
            "mode": "flight",
            "shm": tier.name,
            "paths": paths,
            "keys": keys,
            "columns": ["k"],
            "tenant": name,
            "metrics_dir": metrics_dir,
            "ready_file": os.path.join(tmp, f"ready-{name}"),
            "go_file": go if concurrent else None,
            # 20 ms modeled storage latency: concurrent workers' reads
            # OVERLAP, so the cross-process flight table is exercised
            # for real (local reads finish too fast to collide)
            "read_delay_s": 0.02 if concurrent else 0.0,
        }
        cfg_path = os.path.join(tmp, f"cfg-{name}.json")
        pathlib.Path(cfg_path).write_text(json.dumps(cfg))
        procs.append((name, subprocess.Popen(
            [sys.executable, WORKER_SCRIPT, cfg_path],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )))
    if concurrent:
        import time

        deadline = time.monotonic() + 120.0
        while not all(
            os.path.exists(os.path.join(tmp, f"ready-{n}"))
            for n in names
        ):
            if time.monotonic() > deadline:
                for _, p in procs:
                    p.kill()
                raise TimeoutError("workers never reached the barrier")
            time.sleep(0.01)
        pathlib.Path(go).touch()
    results = []
    for name, p in procs:
        out, err = p.communicate(timeout=180)
        if p.returncode != 0:
            raise RuntimeError(
                f"worker {name} failed rc={p.returncode}:\n"
                f"{err.decode()[-2000:]}"
            )
        results.append(json.loads(out.decode().splitlines()[-1]))
    return results


def main() -> int:
    paths = build_paths()
    per = GROUP * GROUPS
    # probe keys spread over pages and files (all present, even keys)
    keys = [2 * (f * per + g * GROUP + off)
            for f in range(FILES) for g in range(GROUPS)
            for off in (PAGE // 2, 3 * PAGE)]
    tmp = tempfile.mkdtemp(prefix="pftpu_proc_smoke_")
    metrics_dir = os.path.join(tmp, "metrics")
    os.makedirs(metrics_dir)
    try:
        with ShmCacheTier.create(data_bytes=32 << 20,
                                 meta_bytes=8 << 20) as tier:
            names = [f"w{i}" for i in range(WORKERS)]
            results = run_workers(tier, paths, keys, names, metrics_dir,
                                  tmp, concurrent=True)

            # -- 1: cross-process single-flight ------------------------------
            all_ranges = []
            for r in results:
                if r["rows"] != len(keys):
                    return fail(f"worker {r['tenant']} read {r['rows']} "
                                f"rows, expected {len(keys)}")
                all_ranges.extend(map(tuple, r["ranges"]))
            if len(all_ranges) != len(set(all_ranges)):
                dupes = len(all_ranges) - len(set(all_ranges))
                return fail(
                    f"{dupes} storage range(s) read MORE THAN ONCE across "
                    f"{WORKERS} workers — cross-process single-flight broken"
                )
            waits = tier.stats()["singleflight_waits"]
            if not waits >= 1:
                return fail(
                    "no cross-process single-flight wait was ever taken — "
                    "the workers never contended, the law went unexercised"
                )
            print(f"process_serving_smoke: single-flight ok — "
                  f"{len(set(all_ranges))} unique ranges, each read once "
                  f"across {WORKERS} workers ({waits} cross-process waits)")

            # -- 2: warm worker, zero storage reads --------------------------
            warm = run_workers(tier, paths, keys, ["warm"], metrics_dir,
                               tmp, concurrent=False)[0]
            if warm["rows"] != len(keys):
                return fail(f"warm worker read {warm['rows']} rows")
            if warm["ranges"]:
                return fail(
                    f"warm worker touched storage {len(warm['ranges'])} "
                    "time(s); a warm segment must serve every byte"
                )
            hits = warm["counters"].get("serve.shm_hits", 0)
            if not hits > 0:
                return fail("warm worker recorded no shm hits")
            print(f"process_serving_smoke: warm worker ok — 0 storage "
                  f"reads, {hits} shm hits (hit-rate 1.0)")

            # -- 3: per-tenant disjointness + the metrics fold ---------------
            from parquet_floor_tpu.utils.metrics_export import (
                merge_snapshot_dir,
                parse_prometheus,
            )

            per_worker = {}
            for name in names + ["warm"]:
                snap = json.loads(pathlib.Path(
                    os.path.join(metrics_dir, f"worker-{name}.json")
                ).read_text())
                probes = snap["counters"].get("serve.lookup_probes", 0)
                if probes != len(keys):
                    return fail(
                        f"worker {name} snapshot carries {probes} probes, "
                        f"expected exactly its own {len(keys)} — "
                        "per-process attribution leaked"
                    )
                per_worker[name] = snap
            merged = merge_snapshot_dir(metrics_dir)
            want = len(keys) * (WORKERS + 1)
            got = merged["counters"].get("serve.lookup_probes", 0)
            if got != want:
                return fail(f"merged fold carries {got} probes, "
                            f"expected {want}")
            # the same fold over HTTP, through the aggregator endpoint
            from parquet_floor_tpu.utils import trace

            with trace.scope() as t, trace.serve_metrics(
                0, tracer=t, snapshot_dir=metrics_dir
            ) as server:
                text = urllib.request.urlopen(
                    server.url(), timeout=10
                ).read().decode()
                samples = parse_prometheus(text)
            if samples.get("pftpu_serve_lookup_probes") != want:
                return fail(
                    f"HTTP aggregator scrape says "
                    f"{samples.get('pftpu_serve_lookup_probes')} probes, "
                    f"expected {want}"
                )
            print(f"process_serving_smoke: metrics fold ok — "
                  f"{WORKERS + 1} worker snapshots, merged probes {got}, "
                  "HTTP aggregate matches")

            # -- 4: the daemon contract --------------------------------------
            rc = check_daemon(paths, metrics_dir, want)
            if rc:
                return rc
        print("process_serving_smoke: PASS")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def check_daemon(paths: list, metrics_dir: str, worker_probes: int) -> int:
    per = GROUP * GROUPS
    with Serving(prefetch_bytes=16 << 20, device_lanes=2) as srv:
        with Dataset(paths, "k", cache=srv.cache) as ds:
            with ServeDaemon(srv, {"smoke": ds},
                             metrics_dir=metrics_dir) as daemon:
                with DaemonClient("127.0.0.1", daemon.port, "cli-a",
                                  weight=2.0) as ca, \
                        DaemonClient("127.0.0.1", daemon.port,
                                     "cli-b") as cb:
                    for i in range(6):
                        rows = ca.lookup("smoke", 2 * i * PAGE,
                                         columns=["k"])
                        if len(rows) != 1:
                            return fail(f"daemon lookup returned {rows}")
                    got, cur = [], None
                    while True:
                        page, cur = cb.range_page(
                            "smoke", 0, 4 * PAGE, page_rows=23,
                            cursor=cur,
                        )
                        got.extend(page)
                        if cur is None:
                            break
                    want_rows = ds.range(0, 4 * PAGE)
                    if got != want_rows:
                        return fail(
                            f"daemon paged range returned {len(got)} rows, "
                            f"expected {len(want_rows)}"
                        )
                    # per-connection tenant attribution
                    ta = srv.tenant("cli-a", 2.0)
                    tb = srv.tenant("cli-b")
                    pa = ta.tracer.counters().get("serve.lookup_probes", 0)
                    if pa != 6:
                        return fail(f"tenant cli-a carries {pa} probes, "
                                    "expected its own 6")
                    pages = tb.tracer.counters().get("serve.cursor_pages", 0)
                    if not pages >= 2:
                        return fail("tenant cli-b's cursor pages were not "
                                    "attributed to it")
                    # the daemon's metrics op folds the WORKER snapshots
                    m = ca.metrics()
                    folded = m["counters"].get("serve.lookup_probes", 0)
                    if folded < worker_probes + 6:
                        return fail(
                            f"daemon metrics op folded {folded} probes, "
                            f"expected >= {worker_probes + 6} "
                            "(workers + its own tenants)"
                        )
                    if not daemon.drain(10.0):
                        return fail("daemon drain did not complete clean")
                    r = ca.request("lookup", dataset="smoke", key=0)
                    if r.get("code") != "draining":
                        return fail(f"post-drain probe answered {r!r}, "
                                    "expected a draining rejection")
    print(f"process_serving_smoke: daemon ok — attribution, paging "
          f"({per} row corpus), metrics fold, clean drain")
    return 0


if __name__ == "__main__":
    sys.exit(main())
