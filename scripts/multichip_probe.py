#!/usr/bin/env python
"""One multi-chip scan measurement (docs/multichip.md) — the subprocess
half of bench.py's multichip leg.

Usage: multichip_probe.py PARQUET_FILE

Runs THREE passes over every row group of ``PARQUET_FILE`` through the
device engine and prints ONE JSON line:

* **serial** — the sequential per-group reader loop (no pipeline): the
  overlap baseline, its inflate wall runs on the one consumer thread;
* **single** — the pipelined scan with the mesh OFF
  (``PFTPU_MESH_DEVICES=0``): the single-chip throughput reference;
* **mesh** — the pipelined scan round-robined across the local devices
  (``PFTPU_MESH_DEVICES=<k>``).

The digest is a CRC over every delivered group's CANONICAL content
(strings trimmed to their lengths — pad widths follow staging order and
are not contractual) so the three passes must match bit-for-bit.  The
overlap fraction is the share of total ``inflate`` span wall that ran
concurrently with pipeline spans (stage/inflate/ship/decode) on OTHER
threads — what the stage pool actually hid under device work.

The caller owns device-count forcing: on CPU it sets
``XLA_FLAGS=--xla_force_host_platform_device_count=4`` before this
process imports jax.
"""

import json
import os
import sys
import time
import zlib

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_PIPE_SPANS = ("stage", "inflate", "ship", "decode")


def _intervals(events):
    """Closed ``(name, tid, t0, t1)`` spans off the raw timeline."""
    open_, out = {}, []
    for ph, name, ts, tid, _attrs in events:
        if ph == "B":
            open_.setdefault((tid, name), []).append(ts)
        elif ph == "E":
            stack = open_.get((tid, name))
            if stack:
                out.append((name, tid, stack.pop(), ts))
    return out


def _overlap_fraction(events):
    """Share of total inflate wall covered by other-thread pipeline
    spans; None when no inflate span closed (nothing to measure)."""
    iv = _intervals(events)
    inflate = [(t0, t1, tid) for n, tid, t0, t1 in iv if n == "inflate"]
    others = [(t0, t1, tid) for n, tid, t0, t1 in iv if n in _PIPE_SPANS]
    total = sum(t1 - t0 for t0, t1, _ in inflate)
    if total <= 0:
        return None
    covered = 0.0
    for t0, t1, tid in inflate:
        segs = sorted(
            (max(t0, a), min(t1, b))
            for a, b, otid in others
            if otid != tid and b > t0 and a < t1
        )
        hi = t0
        for a, b in segs:
            a = max(a, hi)
            if b > a:
                covered += b - a
                hi = b
    return covered / total


def _digest(cols, digest):
    import numpy as np

    for name in sorted(cols):
        c = cols[name]
        v = np.asarray(c.values)
        ln = None if c.lengths is None else np.asarray(c.lengths)
        m = getattr(c, "mask", None)
        if ln is not None and v.ndim == 2:
            digest = zlib.crc32(np.ascontiguousarray(ln).tobytes(), digest)
            digest = zlib.crc32(
                b"".join(v[i, : int(ln[i])].tobytes()
                         for i in range(v.shape[0])),
                digest,
            )
        else:
            if m is not None:
                mm = np.asarray(m)
                v = np.where(mm, np.zeros_like(v), v)
            digest = zlib.crc32(np.ascontiguousarray(v).tobytes(), digest)
        if m is not None:
            digest = zlib.crc32(
                np.ascontiguousarray(np.asarray(m)).tobytes(), digest
            )
    return digest


def main(argv) -> int:
    if len(argv) != 2:
        print("usage: multichip_probe.py PARQUET_FILE", file=sys.stderr)
        return 2
    path = argv[1]

    import jax

    jax.config.update("jax_enable_x64", True)
    from parquet_floor_tpu import ParquetFileReader
    from parquet_floor_tpu.scan import scan_device_groups
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader
    from parquet_floor_tpu.utils import trace

    devs = jax.local_devices()
    k = min(4, len(devs))
    platform = devs[0].platform if devs else "none"

    def serial_pass():
        os.environ["PFTPU_MESH_DEVICES"] = "0"
        with trace.scope() as t:
            t0 = time.perf_counter()
            digest, groups = 0, 0
            with TpuRowGroupReader(ParquetFileReader(path)) as r:
                for gi in range(len(r.reader.row_groups)):
                    digest = _digest(r.read_row_group(gi), digest)
                    groups += 1
            wall = time.perf_counter() - t0
        return wall, digest, groups, t

    def scan_pass(mesh_k):
        os.environ["PFTPU_MESH_DEVICES"] = str(mesh_k)
        with trace.scope() as t:
            t0 = time.perf_counter()
            digest, groups = 0, 0
            for _fi, _gi, cols in scan_device_groups([path]):
                digest = _digest(cols, digest)
                groups += 1
            wall = time.perf_counter() - t0
        return wall, digest, groups, t

    wall_serial, dig_serial, groups, t_serial = serial_pass()
    wall_single, dig_single, g_single, _ = scan_pass(0)
    wall_mesh, dig_mesh, g_mesh, t_mesh = scan_pass(k)
    c = t_mesh.counters()

    print(json.dumps({
        "platform": platform,
        "devices": k,
        "groups": groups,
        "wall_serial_ms": round(wall_serial * 1e3, 1),
        "wall_single_ms": round(wall_single * 1e3, 1),
        "wall_mesh_ms": round(wall_mesh * 1e3, 1),
        "bit_identical": dig_serial == dig_single == dig_mesh
        and groups == g_single == g_mesh,
        "mesh_groups": c.get("engine.mesh_groups", 0),
        "launches": c.get("engine.launches", 0),
        "overlap_fraction": _overlap_fraction(t_mesh.events()),
        "overlap_serial": _overlap_fraction(t_serial.events()) or 0.0,
        "events_dropped": c.get("trace.events_dropped", 0),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
