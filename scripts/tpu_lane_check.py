"""Compile-and-verify the lane-gather RLE kernel on a real TPU.

Runs the compiled (non-interpret) kernel for every ``lane_compiled`` bit
width against the jnp reference expansion. The interpret-mode pytest suite
proves semantics; this proves Mosaic actually lowers each specialization.
Usage: python scripts/tpu_lane_check.py
"""

import os
import sys
import time

import numpy as np

import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from parquet_floor_tpu.format.encodings import rle_hybrid as e_rle
from parquet_floor_tpu.tpu import bitops
from parquet_floor_tpu.tpu.kernels import rle_kernel as plk


def check(bw: int) -> float:
    rng = np.random.default_rng(bw)
    n = 8 * plk.TILE + 1234
    vals = (
        rng.integers(0, 1 << 32, n, dtype=np.uint64) & ((1 << bw) - 1)
    ).astype(np.uint32)
    vals[100:2200] = 3
    vals[plk.TILE : plk.TILE + 900] = np.uint32((1 << bw) - 1)
    stream = e_rle.encode_rle_hybrid(vals, bw)
    table, _ = e_rle.parse_runs(stream, n, bw)
    pad = bitops.bucket_size(max(len(table), 1), 16)
    plan = bitops.run_table_to_device_plan(table, n, pad)
    buf = np.zeros(len(stream) + 8, np.uint8)
    buf[: len(stream)] = np.frombuffer(stream, np.uint8)
    lo, hi = plk.tile_spans(plan["run_out_end"], n)
    args = (
        jnp.asarray(buf),
        jnp.asarray(plan["run_out_end"]),
        jnp.asarray(plan["run_kind"]),
        jnp.asarray(plan["run_value"]),
        jnp.asarray(plan["run_bytebase"]),
        jnp.asarray(lo),
        jnp.asarray(hi),
    )
    t0 = time.perf_counter()
    got = plk.rle_expand_pallas(*args, num_values=n, bit_width=bw)
    got.block_until_ready()
    compile_s = time.perf_counter() - t0
    want = bitops.rle_expand(*args[:5], n, bw)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # steady-state timing
    for _ in range(2):
        plk.rle_expand_pallas(*args, num_values=n, bit_width=bw).block_until_ready()
    t0 = time.perf_counter()
    reps = 20
    for _ in range(reps):
        out = plk.rle_expand_pallas(*args, num_values=n, bit_width=bw)
    out.block_until_ready()
    per = (time.perf_counter() - t0) / reps
    print(
        f"bw={bw:2d} OK  compile={compile_s:6.2f}s  "
        f"steady={per * 1e6:8.1f}us  ({n / per / 1e9:6.2f} Gvals/s)"
    )
    return per


def check_hbm(bw: int) -> float:
    """Compile-and-verify the HBM-plan variant on a run-heavy stream
    (> PL_MAX_RUNS runs: the round-2 gate this formulation lifts)."""
    rng = np.random.default_rng(100 + bw)
    n = 24 * plk.TILE + 411
    base = (
        rng.integers(0, 1 << 32, n // 9 + 1, dtype=np.uint64) & ((1 << bw) - 1)
    ).astype(np.uint32)
    vals = np.repeat(base, 9)[:n]
    vals[plk.TILE - 100 : plk.TILE + 100] = (
        rng.integers(0, 1 << 32, 200, dtype=np.uint64) & ((1 << bw) - 1)
    ).astype(np.uint32)
    stream = e_rle.encode_rle_hybrid(vals, bw)
    table, _ = e_rle.parse_runs(stream, n, bw)
    assert len(table) > plk.PL_MAX_RUNS, len(table)
    pad = bitops.bucket_size(max(len(table), 1), 16)
    plan = bitops.run_table_to_device_plan(table, n, pad)
    buf = np.zeros(len(stream) + 8, np.uint8)
    buf[: len(stream)] = np.frombuffer(stream, np.uint8)
    lo, hi = plk.tile_spans(plan["run_out_end"], n)
    assert plk.max_aligned_span(lo, hi) <= plk.PL_RUN_WIN
    flat = jnp.asarray(
        np.concatenate([
            plan["run_out_end"], plan["run_kind"], plan["run_value"],
            plan["run_bytebase"], np.zeros_like(plan["run_out_end"]),
        ]).astype(np.int32)
    )
    data = jnp.asarray(buf)
    lo_d, hi_d = jnp.asarray(lo), jnp.asarray(hi)
    n_runs = len(plan["run_out_end"])
    t0 = time.perf_counter()
    got = plk.rle_expand_pallas_hbm(
        data, flat, n_runs, lo_d, hi_d, num_values=n, bit_width=bw
    )
    got.block_until_ready()
    compile_s = time.perf_counter() - t0
    want = bitops.rle_expand(
        data,
        jnp.asarray(plan["run_out_end"]),
        jnp.asarray(plan["run_kind"]),
        jnp.asarray(plan["run_value"]),
        jnp.asarray(plan["run_bytebase"]),
        n,
        bw,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    for _ in range(2):
        plk.rle_expand_pallas_hbm(
            data, flat, n_runs, lo_d, hi_d, num_values=n, bit_width=bw
        ).block_until_ready()
    t0 = time.perf_counter()
    reps = 20
    for _ in range(reps):
        out = plk.rle_expand_pallas_hbm(
            data, flat, n_runs, lo_d, hi_d, num_values=n, bit_width=bw
        )
    out.block_until_ready()
    per = (time.perf_counter() - t0) / reps
    print(
        f"bw={bw:2d} OK [hbm {len(table)} runs]  compile={compile_s:6.2f}s  "
        f"steady={per * 1e6:8.1f}us  ({n / per / 1e9:6.2f} Gvals/s)"
    )
    return per


def main() -> int:
    import jax

    dev = jax.devices()[0]
    print(f"device: {dev.platform} {dev.device_kind}")
    widths = [bw for bw in range(1, 33) if plk.lane_compiled(bw)]
    failed = []
    for bw in widths:
        try:
            check(bw)
        except Exception as e:  # noqa: BLE001 - report and continue
            failed.append(bw)
            print(f"bw={bw:2d} FAIL: {type(e).__name__}: {e}")
    for bw in widths:
        try:
            check_hbm(bw)
        except Exception as e:  # noqa: BLE001 - report and continue
            failed.append((bw, "hbm"))
            print(f"bw={bw:2d} FAIL [hbm]: {type(e).__name__}: {e}")
    if failed:
        print(f"FAILED widths: {failed}")
        return 1
    print(f"all {len(widths)} compiled widths verified (smem + hbm plans)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
