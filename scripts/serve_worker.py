#!/usr/bin/env python
"""One serving worker PROCESS for the multi-worker bench/smoke drivers.

Spawned by ``bench.py``'s traffic leg and
``scripts/process_serving_smoke.py``: attaches the shared
:class:`~parquet_floor_tpu.serve.shm_cache.ShmCacheTier` by name,
mounts it under a private in-process ``SharedBufferCache`` (the L1/L2
shape every real worker runs), opens the keyed dataset behind it, and
probes a configured key list — after a file-based start barrier so
concurrent workers really contend.

Config (JSON file, argv[1]):

* ``mode`` — ``"scale"`` (timed throughput: warm the file opens first,
  then time the probe loop) or ``"flight"`` (correctness: everything
  after the barrier, every real storage read RECORDED so the driver
  can assert the cross-process single-flight law).
* ``shm`` — segment name to attach; ``paths`` — the dataset files;
  ``keys`` — the probe keys (``warm_keys`` probed before the barrier
  in scale mode); ``tenant`` — this worker's tenant name.
* ``remote`` — optional ``RemoteProfile`` kwargs: sources become
  seeded ``SimulatedRemoteSource``\\ s (latency-bound storage, the
  scaling phase's truth regime); otherwise local ``FileSource``.
* ``ready_file`` / ``go_file`` — the start barrier; ``metrics_dir`` —
  optional ``write_snapshot`` push directory (the multi-worker
  metrics fold the smoke validates).

Prints one JSON result line: probes, rows, wall seconds, the worker's
tracer counters, recorded storage ranges (flight mode), and the shm
tier's header stats.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from parquet_floor_tpu.io.source import FileSource  # noqa: E402
from parquet_floor_tpu.serve import (  # noqa: E402
    Dataset,
    SharedBufferCache,
    ShmCacheTier,
)
from parquet_floor_tpu.utils import trace  # noqa: E402


class RecordingSource:
    """FileSource wrapper recording every REAL storage range read —
    what reaches here got through both cache tiers, so the driver's
    exactly-once-per-unique-range assertion reads this ledger.
    ``delay_s`` models storage latency, widening each read's in-flight
    window so concurrent workers actually collide on the flight table
    (local reads are too fast to overlap otherwise)."""

    def __init__(self, path: str, ledger: list, index: int,
                 delay_s: float = 0.0):
        self._src = FileSource(path)
        self._ledger = ledger
        self._index = index
        self._delay = float(delay_s)
        self.size = self._src.size
        self.name = self._src.name

    def read_at(self, offset: int, length: int):
        self._ledger.append((self._index, int(offset), int(length)))
        if self._delay:
            time.sleep(self._delay)
        return self._src.read_at(offset, length)

    def read_many(self, ranges):
        ranges = list(ranges)
        for o, n in ranges:
            self._ledger.append((self._index, int(o), int(n)))
        if self._delay:
            time.sleep(self._delay)
        return self._src.read_many(ranges)

    def close(self) -> None:
        self._src.close()


def make_factories(cfg: dict, ledger: list) -> list:
    remote = cfg.get("remote")
    if remote:
        from parquet_floor_tpu.testing import (
            RemoteProfile,
            SimulatedRemoteSource,
        )

        profile = RemoteProfile(**remote)
        seed = int(cfg.get("seed", 0))
        return [
            (lambda p=p, i=i: SimulatedRemoteSource(
                p, profile=profile, seed=seed + i, fetch_threads=4
            ))
            for i, p in enumerate(cfg["paths"])
        ]
    delay = float(cfg.get("read_delay_s", 0.0))
    return [
        (lambda p=p, i=i: RecordingSource(p, ledger, i, delay))
        for i, p in enumerate(cfg["paths"])
    ]


def barrier(cfg: dict) -> None:
    ready = cfg.get("ready_file")
    go = cfg.get("go_file")
    if ready:
        pathlib.Path(ready).touch()
    if go:
        deadline = time.monotonic() + 120.0
        while not os.path.exists(go):
            if time.monotonic() > deadline:
                raise TimeoutError("start barrier never opened")
            time.sleep(0.005)


def main() -> int:
    cfg = json.loads(pathlib.Path(sys.argv[1]).read_text())
    ledger: list = []
    tier = ShmCacheTier.attach(cfg["shm"])
    try:
        with SharedBufferCache(
            data_bytes=int(cfg.get("l1_bytes", 32 << 20)), shm=tier,
        ) as cache, trace.scope() as tracer:
            with Dataset(
                make_factories(cfg, ledger), cfg.get("key_column", "k"),
                cache=cache,
            ) as ds:
                columns = cfg.get("columns")
                rows = 0
                for k in cfg.get("warm_keys", []):
                    rows += len(ds.lookup(k, columns=columns))
                barrier(cfg)
                t0 = time.perf_counter()
                for k in cfg["keys"]:
                    rows += len(ds.lookup(k, columns=columns))
                wall = time.perf_counter() - t0
            shm_stats = tier.stats()
            counters = tracer.counters()
            if cfg.get("metrics_dir"):
                from parquet_floor_tpu.utils.metrics_export import (
                    snapshot,
                    write_snapshot,
                )

                write_snapshot(snapshot(tracer), os.path.join(
                    cfg["metrics_dir"],
                    f"worker-{cfg.get('tenant', os.getpid())}.json",
                ))
    finally:
        tier.close()
    shm_stats.pop("name", None)
    print(json.dumps({
        "tenant": cfg.get("tenant"),
        "probes": len(cfg["keys"]),
        "rows": rows,
        "wall": wall,
        "counters": counters,
        "ranges": ledger,
        "shm_stats": shm_stats,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
