#!/usr/bin/env python
"""Forced-4-device mesh smoke (docs/multichip.md): on a
``--xla_force_host_platform_device_count=4`` CPU mesh (the caller sets
XLA_FLAGS before python starts), a dataset scan with
``PFTPU_MESH_DEVICES=4`` must deliver bit-identically to the
single-device pass, place EVERY group on the mesh with exactly one
fused launch each, and actually spread the groups across all 4 devices
(round-robin floor: each device decodes >= groups // 4).
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def canon(cols):
    import numpy as np

    out = {}
    for name, dc in sorted(cols.items()):
        v = np.asarray(dc.values)
        if getattr(dc, "lengths", None) is not None:
            ls = np.asarray(dc.lengths)
            out[name] = [bytes(r[:l]) for r, l in zip(v, ls)]
        else:
            out[name] = v.tobytes()
    return out


def main() -> int:
    import jax

    jax.config.update("jax_enable_x64", True)
    import numpy as np

    from parquet_floor_tpu import (
        CompressionCodec,
        ParquetFileWriter,
        WriterOptions,
        trace,
        types,
    )
    from parquet_floor_tpu.scan import scan_device_groups

    k = len(jax.local_devices())
    assert k == 4, f"expected a forced 4-device mesh, got {k} device(s)"

    schema = types.message(
        "t",
        types.required(types.INT64).named("a"),
        types.optional(types.DOUBLE).named("d"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
    )
    tmp = tempfile.mkdtemp(prefix="pftpu_mesh_smoke_")
    paths = []
    rng = np.random.default_rng(11)
    for fi in range(2):
        p = os.path.join(tmp, f"f{fi}.parquet")
        with ParquetFileWriter(p, schema, WriterOptions(
            codec=CompressionCodec.SNAPPY, row_group_rows=500,
            data_page_values=250,
        )) as w:
            for g in range(4):
                n = 500
                w.write_columns({
                    "a": np.arange(n, dtype=np.int64) + fi * 10_000 + g,
                    "d": [None if i % 9 == 0 else float(x)
                          for i, x in enumerate(rng.standard_normal(n))],
                    "s": [None if i % 7 == 0 else f"s{(i * 3 + g) % 41}"
                          for i in range(n)],
                })
        paths.append(p)

    def run(mesh):
        os.environ["PFTPU_MESH_DEVICES"] = mesh
        got, devs = [], []
        with trace.scope() as t:
            for fi, gi, cols in scan_device_groups(paths):
                got.append((fi, gi, canon(cols)))
                devs.append(next(iter(
                    jax.tree_util.tree_leaves(
                        [c.values for c in cols.values()]
                    )[0].devices()
                )))
        return got, devs, t.counters(), t.gauges()

    single, _, _, _ = run("0")
    meshed, devs, c, g = run("4")
    groups = len(single)
    assert groups == 8, f"expected 8 groups, got {groups}"
    assert [x[:2] for x in meshed] == [x[:2] for x in single], \
        "mesh delivery order diverged"
    assert meshed == single, "mesh delivery is not bit-identical"
    assert c.get("engine.mesh_groups") == groups, \
        f"mesh placed {c.get('engine.mesh_groups')}/{groups} groups"
    assert c.get("engine.launches") == groups, \
        f"{c.get('engine.launches')} launches for {groups} groups"
    assert g.get("engine.mesh_devices") == 4, \
        f"mesh gauge says {g.get('engine.mesh_devices')} devices"
    per_dev = {d: devs.count(d) for d in set(devs)}
    assert len(per_dev) == 4, \
        f"groups landed on only {len(per_dev)}/4 devices: {per_dev}"
    floor = groups // 4
    assert all(n >= floor for n in per_dev.values()), \
        f"round-robin floor {floor} violated: {per_dev}"
    print(f"mesh smoke ok: {groups} groups bit-identical over 4 devices "
          f"(per-device {sorted(per_dev.values())}, "
          f"{c.get('engine.launches')} launches)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
